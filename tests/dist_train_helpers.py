"""Spawn-able workers for the localhost pserver training test
(reference test_dist_train.py forks pservers with multiprocessing and
connects over localhost gRPC).  Top-level functions so the 'spawn' start
method can pickle them."""
import os
import sys

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# spawn children start with a fresh sys.path that lacks the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np

N_FEAT = 48
N_CLS = 10
LR = 0.5


def build_model():
    import paddle_tpu.fluid as fluid

    img = fluid.layers.data(name="img", shape=[N_FEAT], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    # zero init everywhere -> every process starts from identical params,
    # so sync-SGD losses must match the single-process run exactly
    zinit = fluid.initializer.ConstantInitializer(0.0)
    pred = fluid.layers.fc(
        input=img, size=N_CLS, act="softmax",
        param_attr=fluid.ParamAttr(name="fc_w", initializer=zinit),
        bias_attr=fluid.ParamAttr(name="fc_b", initializer=zinit))
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=pred, label=label))
    fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
    return loss


def make_batch(step):
    rng = np.random.RandomState(1234 + step)
    x = rng.randn(64, N_FEAT).astype(np.float32)
    proj = np.random.RandomState(7).randn(N_FEAT, N_CLS)
    y = np.argmax(x @ proj, axis=1).astype(np.int64)[:, None]
    return x, y


def run_local_baseline(steps):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for s in range(steps):
            x, y = make_batch(s)
            l, = exe.run(main, feed={"img": x, "label": y},
                         fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
    return losses


def _transpile(trainer_id, pservers, trainers):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = build_model()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=trainer_id, program=main,
                startup_program=startup, pservers=pservers,
                trainers=trainers, min_block_size=64)
    return t, main, startup, scope, loss


def run_pserver(endpoint, pservers, trainers):
    import paddle_tpu.fluid as fluid

    t, main, startup, scope, loss = _transpile(0, pservers, trainers)
    ps_prog = t.get_pserver_program(endpoint)
    ps_startup = t.get_startup_program(endpoint, ps_prog)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(ps_startup)
        exe.run(ps_prog)   # blocks until all trainers SendComplete


def run_trainer(trainer_id, pservers, trainers, steps, queue):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed.rpc import RPCClient

    t, main, startup, scope, loss = _transpile(trainer_id, pservers,
                                               trainers)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for s in range(steps):
            # both trainers feed the SAME batch: the pserver's grad mean
            # then equals the single-process grad, so losses must match
            x, y = make_batch(s)
            l, = exe.run(t.get_trainer_program(),
                         feed={"img": x, "label": y}, fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
    RPCClient.instance().send_complete(t.pserver_endpoints)
    queue.put((trainer_id, losses))
