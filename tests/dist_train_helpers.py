"""Spawn-able workers for the localhost pserver training test
(reference test_dist_train.py forks pservers with multiprocessing and
connects over localhost gRPC).  Top-level functions so the 'spawn' start
method can pickle them."""
import os
import sys

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
# FORCE cpu (not setdefault): a rig exporting JAX_PLATFORMS=axon would
# otherwise drag every spawned worker into accelerator-plugin init
os.environ["JAX_PLATFORMS"] = "cpu"

# spawn children start with a fresh sys.path that lacks the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np

N_FEAT = 48
N_CLS = 10
LR = 0.5


def build_model(kind="softmax"):
    import paddle_tpu.fluid as fluid

    # zero init everywhere -> every process starts from identical params,
    # so sync-SGD losses must match the single-process run exactly
    zinit = fluid.initializer.ConstantInitializer(0.0)
    if kind in ("emb_sparse", "emb_dense", "emb_dist"):
        ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        # NON-zero constant inits (still identical across processes):
        # with emb_w=fc_w=0 both grads vanish identically and the test
        # could not distinguish a broken sparse path from a working one
        emb = fluid.layers.embedding(
            ids, size=[50, 8],
            is_sparse=(kind in ("emb_sparse", "emb_dist")),
            is_distributed=(kind == "emb_dist"),
            param_attr=fluid.ParamAttr(
                name="emb_w",
                initializer=fluid.initializer.ConstantInitializer(0.02)))
        pooled = fluid.layers.reduce_mean(emb, dim=1)   # [N, 8]
        pred = fluid.layers.fc(
            input=pooled, size=1,
            param_attr=fluid.ParamAttr(
                name="fc_w",
                initializer=fluid.initializer.ConstantInitializer(0.1)),
            bias_attr=fluid.ParamAttr(name="fc_b", initializer=zinit))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
        return loss
    img = fluid.layers.data(name="img", shape=[N_FEAT], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    pred = fluid.layers.fc(
        input=img, size=N_CLS, act="softmax",
        param_attr=fluid.ParamAttr(name="fc_w", initializer=zinit),
        bias_attr=fluid.ParamAttr(name="fc_b", initializer=zinit))
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=pred, label=label))
    fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
    return loss


def make_batch(step, kind="softmax"):
    rng = np.random.RandomState(1234 + step)
    if kind in ("emb_sparse", "emb_dense", "emb_dist"):
        # one FIXED batch (step-independent): squared loss on a linear
        # model then descends monotonically, a clean learning signal
        rng = np.random.RandomState(1234)
        ids = rng.randint(0, 50, (32, 4)).astype(np.int64)
        y = (np.sin(ids).sum(1, keepdims=True) * 0.1).astype(np.float32)
        return {"ids": ids, "y": y}
    x = rng.randn(64, N_FEAT).astype(np.float32)
    proj = np.random.RandomState(7).randn(N_FEAT, N_CLS)
    y = np.argmax(x @ proj, axis=1).astype(np.int64)[:, None]
    return {"img": x, "label": y}


def run_local_baseline(steps, kind="softmax"):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = build_model(kind)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for s in range(steps):
            l, = exe.run(main, feed=make_batch(s, kind),
                         fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
    return losses


def _transpile(trainer_id, pservers, trainers, kind="softmax",
               sync_mode=True):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = build_model(kind)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=trainer_id, program=main,
                startup_program=startup, pservers=pservers,
                trainers=trainers, min_block_size=64,
                sync_mode=sync_mode)
    return t, main, startup, scope, loss


def _apply_env(env):
    """Install per-worker env BEFORE the first paddle/flag import reads
    it (spawn children import this module fresh): fault specs, retry
    knobs, and checkpoint roots all ride environment variables."""
    if env:
        os.environ.update(env)


def _dump_telemetry():
    """Explicit per-process trace dump (FLAGS_telemetry_dump_dir):
    spawned workers should not rely on atexit ordering to leave their
    half of a merged distributed trace."""
    try:
        from paddle_tpu.observability.trace import TRACER
        TRACER.dump_if_configured()
    except Exception:
        pass


def run_pserver(endpoint, pservers, trainers, kind="softmax",
                sync_mode=True, env=None):
    _apply_env(env)
    import paddle_tpu.fluid as fluid

    t, main, startup, scope, loss = _transpile(0, pservers, trainers,
                                               kind, sync_mode)
    ps_prog = t.get_pserver_program(endpoint)
    ps_startup = t.get_startup_program(endpoint, ps_prog)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(ps_startup)
        exe.run(ps_prog)   # blocks until all trainers SendComplete
    _dump_telemetry()


def run_trainer(trainer_id, pservers, trainers, steps, queue,
                kind="softmax", sync_mode=True, env=None):
    _apply_env(env)
    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed.rpc import RPCClient

    t, main, startup, scope, loss = _transpile(trainer_id, pservers,
                                               trainers, kind,
                                               sync_mode)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for s in range(steps):
            # both trainers feed the SAME batch: the pserver's grad mean
            # then equals the single-process grad, so losses must match
            l, = exe.run(t.get_trainer_program(),
                         feed=make_batch(s, kind), fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
    RPCClient.instance().send_complete(t.pserver_endpoints)
    _dump_telemetry()
    queue.put((trainer_id, losses))
