"""In-program CSP: channel + go ops INSIDE a fluid ProgramDesc
(reference framework/channel.h:33, operators/channel_*_op.cc, go_op.cc;
front-end concurrency.py Go:27/make_channel:279).  A producer go-block
computes on device and sends through a channel; the main block receives
and keeps computing — all expressed as program ops, surviving
serialization."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import concurrency as C


def test_program_channel_producer_consumer(prog_scope, exe):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    ch = C.program_make_channel(dtype="float32", capacity=2)

    with C.ProgramGo():
        # producer sub-block: a real device computation feeds the send
        doubled = fluid.layers.scale(x, scale=2.0)
        C.program_channel_send(ch, doubled)

    got = fluid.layers.data(name="got_buf", shape=[4], dtype="float32")
    C.program_channel_recv(ch, got)
    out = fluid.layers.scale(got, scale=10.0)

    exe.run(startup)
    xs = np.arange(8, dtype=np.float32).reshape(2, 4)
    res, = exe.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(res), xs * 20.0, rtol=1e-5)

    from paddle_tpu.ops.concurrency_ops import join_go_threads
    join_go_threads(scope)


def test_program_channel_roundtrip_serialized(prog_scope, exe):
    """The CSP structure lives in the ProgramDesc: serialize, reparse,
    run — same behavior (this is exactly what the host-thread-only CSP
    could not do)."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[3], dtype="float32")
    ch = C.program_make_channel(dtype="float32", capacity=1)
    with C.ProgramGo():
        C.program_channel_send(ch, x)
    got = fluid.layers.data(name="got2", shape=[3], dtype="float32")
    C.program_channel_recv(ch, got)
    out = fluid.layers.scale(got, scale=3.0)

    reparsed = fluid.Program.parse_from_string(
        main.serialize_to_string())
    exe.run(startup)
    xs = np.ones((1, 3), np.float32)
    res, = exe.run(reparsed, feed={"x": xs},
                   fetch_list=[out.name])
    np.testing.assert_allclose(np.asarray(res), xs * 3.0, rtol=1e-5)
    from paddle_tpu.ops.concurrency_ops import join_go_threads
    join_go_threads(scope)


def test_channel_close_unblocks_recv(prog_scope, exe):
    """close -> drained recv reports Status=False (reference
    channel_recv:385 Status out)."""
    main, startup, scope = prog_scope
    ch = C.program_make_channel(dtype="float32", capacity=1)
    C.program_channel_close(ch)
    got = fluid.layers.data(name="g3", shape=[1], dtype="float32")
    st = C.program_channel_recv(ch, got)
    exe.run(startup)
    sv, = exe.run(main, feed={}, fetch_list=[st.name])
    assert not bool(np.asarray(sv).ravel()[0])


def test_go_thread_records_pruned_across_steps(prog_scope, exe):
    """A training loop executing a main-block go op each step must not
    grow scope._go_threads unboundedly — finished clean records are
    pruned at the next launch."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    ch = C.program_make_channel(dtype="float32", capacity=4)
    with C.ProgramGo():
        C.program_channel_send(ch, x)
    got = fluid.layers.data(name="gp", shape=[2], dtype="float32")
    C.program_channel_recv(ch, got)
    exe.run(startup)
    xs = np.ones((1, 2), np.float32)
    for _ in range(20):
        exe.run(main, feed={"x": xs}, fetch_list=[got])
    from paddle_tpu.ops.concurrency_ops import join_go_threads
    join_go_threads(scope)
    # after join the list is empty; the invariant under test is that it
    # never accumulated 20 dead records mid-loop
    exe.run(main, feed={"x": xs}, fetch_list=[got])
    assert len(scope._go_threads) <= 2
    join_go_threads(scope)


def test_dead_go_routine_closes_its_channels(prog_scope, exe):
    """A go routine that dies must close the channels its sub-block
    touches, so a blocked main-block recv observes ChannelClosed
    (Status=False) instead of hanging forever."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    ch = C.program_make_channel(dtype="float32", capacity=1)
    with C.ProgramGo():
        bad = fluid.layers.scale(x, scale=1.0)
        C.program_channel_send(ch, bad)
    got = fluid.layers.data(name="gd", shape=[2], dtype="float32")
    st = C.program_channel_recv(ch, got)
    exe.run(startup)
    # feed omits x entirely AND the var is absent from the scope -> the
    # routine raises on the missing input before sending
    sv, = exe.run(main, feed={}, fetch_list=[st.name])
    assert not bool(np.asarray(sv).ravel()[0])
    # the error is still surfaced on join
    from paddle_tpu.ops.concurrency_ops import join_go_threads
    try:
        join_go_threads(scope)
        raised = False
    except Exception:
        raised = True
    assert raised


def test_dead_routine_spares_fan_in_channel(prog_scope, exe):
    """A dying routine must NOT close a channel that a healthy sibling
    sender still feeds (fan-in): only sole-sender channels are closed
    on death."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    ch = C.program_make_channel(dtype="float32", capacity=2)
    with C.ProgramGo():          # healthy producer
        C.program_channel_send(ch, x)
    with C.ProgramGo():          # dies (reads a var that is never fed)
        dead = fluid.layers.data(name="never_fed", shape=[2],
                                 dtype="float32")
        C.program_channel_send(ch, fluid.layers.scale(dead, scale=1.0))
    got = fluid.layers.data(name="gf", shape=[2], dtype="float32")
    st = C.program_channel_recv(ch, got)
    exe.run(startup)
    xs = np.full((1, 2), 7.0, np.float32)
    sv, g = exe.run(main, feed={"x": xs}, fetch_list=[st.name, got])
    # the healthy sibling's value arrives with Status=True
    assert bool(np.asarray(sv).ravel()[0])
    np.testing.assert_allclose(np.asarray(g), xs, rtol=1e-6)
    scope._go_threads = []  # the dead routine's error is expected


def test_go_block_captures_parent_temp(prog_scope, exe):
    """A go routine reading a temporary computed by the PARENT block
    must capture it at launch (reference go_op X inputs) — this used to
    deadlock: the temp lived only in the traced env, the routine died
    on the missing var, and recv blocked forever."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.scale(x, scale=5.0)  # parent-block temp
    ch = C.program_make_channel(dtype="float32", capacity=1)
    with C.ProgramGo():
        C.program_channel_send(ch, h)
    got = fluid.layers.data(name="got_t", shape=[4], dtype="float32")
    C.program_channel_recv(ch, got)
    exe.run(startup)
    xs = np.arange(4, dtype=np.float32).reshape(1, 4)
    res, = exe.run(main, feed={"x": xs}, fetch_list=[got])
    np.testing.assert_allclose(np.asarray(res), xs * 5.0, rtol=1e-5)
    from paddle_tpu.ops.concurrency_ops import join_go_threads
    join_go_threads(scope)


def test_program_select_recv_takes_ready_channel(prog_scope, exe):
    """In-program select (ISSUE 8 parity rider; reference
    operators/select_op.cc): a producer go-routine feeds channel B;
    select over (recv A, recv B) takes the ready case, CaseIndex names
    it, and the received value lands in the case's Out var."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="sx", shape=[3], dtype="float32")
    ch_a = C.program_make_channel(dtype="float32", capacity=1)
    ch_b = C.program_make_channel(dtype="float32", capacity=1)
    with C.ProgramGo():
        C.program_channel_send(ch_b, x)
    got_a = fluid.layers.data(name="sel_a", shape=[3], dtype="float32")
    got_b = fluid.layers.data(name="sel_b", shape=[3], dtype="float32")
    idx = C.program_select([("recv", ch_a, got_a),
                            ("recv", ch_b, got_b)], timeout=10.0)
    out = fluid.layers.scale(got_b, scale=5.0)
    exe.run(startup)
    xs = np.arange(3, dtype=np.float32).reshape(1, 3)
    iv, ov = exe.run(main, feed={"sx": xs}, fetch_list=[idx, out])
    assert int(np.asarray(iv).ravel()[0]) == 1  # case 1 = recv B
    np.testing.assert_allclose(np.asarray(ov), xs * 5.0, rtol=1e-6)
    from paddle_tpu.ops.concurrency_ops import join_go_threads
    join_go_threads(scope)


def test_program_select_default_and_send(prog_scope, exe):
    """Nothing ready -> the default case runs; a send case delivers
    into a buffered channel and a later recv sees the value."""
    main, startup, scope = prog_scope
    empty = C.program_make_channel(dtype="float32", capacity=0)
    buf = C.program_make_channel(dtype="float32", capacity=2)
    x = fluid.layers.data(name="dx", shape=[2], dtype="float32")
    # select 1: recv on an empty rendezvous channel vs default
    idx1 = C.program_select([("recv", empty,
                              fluid.layers.data(name="d_got", shape=[2],
                                                dtype="float32")),
                             ("default",)])
    # select 2: send into the buffered channel (always ready)
    idx2 = C.program_select([("send", buf, x)], timeout=10.0)
    got = fluid.layers.data(name="d_got2", shape=[2], dtype="float32")
    C.program_channel_recv(buf, got)
    exe.run(startup)
    xs = np.full((1, 2), 7.0, np.float32)
    i1, i2, gv = exe.run(main, feed={"dx": xs},
                         fetch_list=[idx1, idx2, got])
    assert int(np.asarray(i1).ravel()[0]) == 1  # default case position
    assert int(np.asarray(i2).ravel()[0]) == 0
    np.testing.assert_allclose(np.asarray(gv), xs, rtol=0)


def test_program_select_roundtrip_serialized(prog_scope, exe):
    """The select structure survives proto round-trip: serialize,
    reparse, run — same chosen case and value (the VERDICT 'missing'
    item: select as part of the serialized program)."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="rx", shape=[2], dtype="float32")
    ch = C.program_make_channel(dtype="float32", capacity=1)
    with C.ProgramGo():
        C.program_channel_send(ch, x)
    got = fluid.layers.data(name="r_got", shape=[2], dtype="float32")
    idx = C.program_select([("recv", ch, got)], timeout=10.0)
    out = fluid.layers.scale(got, scale=2.0)
    reparsed = fluid.Program.parse_from_string(
        main.serialize_to_string())
    exe.run(startup)
    xs = np.ones((1, 2), np.float32)
    iv, ov = exe.run(reparsed, feed={"rx": xs},
                     fetch_list=[idx.name, out.name])
    assert int(np.asarray(iv).ravel()[0]) == 0
    np.testing.assert_allclose(np.asarray(ov), xs * 2.0, rtol=1e-6)
    from paddle_tpu.ops.concurrency_ops import join_go_threads
    join_go_threads(scope)


def test_program_select_closed_channel_yields_typed_zero(prog_scope,
                                                         exe):
    """select recv on a closed+drained channel terminates with the
    typed zero channel_recv produces (no hang on a dead producer)."""
    main, startup, scope = prog_scope
    ch = C.program_make_channel(dtype="float32", capacity=1)
    C.program_channel_close(ch)
    got = fluid.layers.data(name="c_got", shape=[1], dtype="float32")
    idx = C.program_select([("recv", ch, got)], timeout=10.0)
    exe.run(startup)
    iv, gv = exe.run(main, feed={}, fetch_list=[idx, got])
    assert int(np.asarray(iv).ravel()[0]) == 0
    assert np.asarray(gv).ravel()[0] == 0.0
