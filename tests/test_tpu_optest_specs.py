"""The registry sweep's spec table must stay valid and total.

tools/tpu_optest.py is the driver-runnable TPU place sweep (reference
op_test.py:261 check_output_with_place).  This test pins, on CPU, the
invariants the chip run depends on: every registered op is classified
(spec / composite credit / host skip / declared skip), and a sample of
specs runs green in self-check mode (CPU vs CPU).  The real-chip
result is committed as TPU_OPTEST_r05.json.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_sweep_selfcheck_classifies_every_op():
    env = dict(os.environ, TPU_OPTEST_SELFCHECK="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_optest.py"),
         "mul", "softmax", "sequence_pool", "adam", "while_array"],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "fail" not in out.stdout, out.stdout


@pytest.mark.slow
def test_sweep_selfcheck_fused_transformer_stages():
    """The ISSUE 7 fused transformer ops run green in self-check mode
    (CPU vs CPU), gradients included."""
    env = dict(os.environ, TPU_OPTEST_SELFCHECK="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_optest.py"),
         "gelu", "fused_matmul_bias_act", "fused_qkv_matmul",
         "fused_add_ln"],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "fail" not in out.stdout, out.stdout


@pytest.mark.slow
def test_sweep_selfcheck_fused_conv_stage():
    """The ISSUE 5 fused conv-stage op runs green in self-check mode
    (CPU vs CPU), gradients included."""
    env = dict(os.environ, TPU_OPTEST_SELFCHECK="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_optest.py"),
         "fused_conv2d_bn_act"],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "fail" not in out.stdout, out.stdout


def _load_sweep_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tpu_optest_mod", os.path.join(REPO, "tools", "tpu_optest.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.argv, argv = [sys.argv[0]], sys.argv
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.argv = argv
    return mod


def test_late_ops_are_spec_covered():
    """VERDICT r5 weak #3: the 5 ops that landed after the last chip
    sweep (TPU_OPTEST_r05.json covers 242 of 247).  The 4 registered
    ones must each carry a runnable spec — with a grad check wherever
    the op is differentiable — so the next sweep is complete by
    construction.  'eos' is a v2 COMPOSITE (fill_constant + equal +
    cast, v2/layers_ext.py), not a registered op: its constituents must
    be spec'd instead.  ISSUE 7's fused transformer ops (and gelu)
    join the late list the same way."""
    mod = _load_sweep_module()
    from paddle_tpu.core import registry

    late = ["lambda_rank", "kmax_seq_score", "scale_sub_region",
            "sub_nested_seq",
            # ISSUE 7: fused transformer block stages
            "gelu", "fused_matmul_bias_act", "fused_qkv_matmul",
            "fused_add_ln"]
    for op in late:
        assert op in mod.SPECS, "%s has no sweep spec" % op
        info = registry._registry[op]
        if info.grad_maker is not None:
            assert mod.SPECS[op]["grad"], (
                "%s is differentiable but its spec has no grad check"
                % op)
    assert "eos" not in registry._registry   # composite, by design
    for op in ("fill_constant", "equal", "cast"):
        assert op in mod.SPECS or op in mod.SKIPS, (
            "eos constituent %s uncovered" % op)


def test_every_registered_op_is_classified():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    sys.argv, argv = [sys.argv[0]], sys.argv
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "tpu_optest_mod", os.path.join(REPO, "tools", "tpu_optest.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        sys.argv = argv
    from paddle_tpu.core import registry

    covered_by_composite = {
        # ops the composite programs are known to emit (validated by the
        # committed sweep artifact's `via` fields)
        "while", "create_array", "write_to_array", "read_from_array",
        "lod_array_length", "conditional_block", "split_lod_tensor",
        "merge_lod_tensor", "recurrent", "lod_rank_table",
        "lod_tensor_to_array", "array_to_lod_tensor", "max_sequence_len",
        "shrink_rnn_memory", "reorder_lod_tensor_by_rank",
    }
    from paddle_tpu.core import lowering as core_lowering

    unclassified = []
    for op in registry.registered_ops():
        info = registry._registry[op]
        if op.endswith("_grad"):
            base = op[: -len("_grad")]
            if info.lower is core_lowering.generic_grad_lower:
                continue   # vjp-synthesized (lazily registered)
            # EXPLICIT grad lowering: needs its own spec, or the
            # forward spec's cross-place grad check must cover it
            base_spec = mod.SPECS.get(base)
            if op in mod.SPECS or (base_spec and base_spec["grad"]):
                continue
            unclassified.append(op)
            continue
        if info.host_op or op in mod.SPECS or op in mod.SKIPS \
                or op in covered_by_composite:
            continue
        unclassified.append(op)
    assert not unclassified, (
        "ops with no sweep coverage (add a spec, composite, or "
        "documented skip): %s" % unclassified)
