"""Failure-path machinery: RetryPolicy, FaultInjector, sender-dedup'd
round replay, durable pserver checkpoints, trainer-lease expiry, the
barrier watchdog, and (slow) full process-kill recovery runs.

Reference analogs: go/pserver/client retry + etcd re-resolution,
go/master/service.go:368 checkTimeout, listen_and_serv sync loop.
"""
import multiprocessing as mp
import os
import socket
import threading
import time

import numpy as np
import pytest

import dist_train_helpers as H
from paddle_tpu.core.scope import Scope
from paddle_tpu.distributed.resilience import (DeadlineExceeded,
                                               EndpointResolver,
                                               FaultInjector,
                                               InjectedFault, RetryPolicy,
                                               WatchdogTimeout,
                                               install_faults)
from paddle_tpu.distributed.rpc import (RPCClient, VariableServer,
                                        _dec_tensor, _enc_tensor,
                                        _pack_round_sender,
                                        _unpack_round_sender)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _clean_faults():
    """Never leak an injector (or the RPCClient singleton's step) into
    another test."""
    install_faults("")
    yield
    install_faults("")
    RPCClient.reset()


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_retry_backoff_exponential_capped_jittered():
    import random

    p = RetryPolicy(base_backoff=0.1, max_backoff=1.0, multiplier=2.0,
                    jitter=0.5, rng=random.Random(0))
    raws = [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]  # capped at max_backoff
    for attempt, raw in enumerate(raws, start=1):
        b = p.backoff(attempt)
        assert 0.5 * raw <= b <= 1.5 * raw


def test_retry_classification():
    import grpc

    assert RetryPolicy.is_retryable(ConnectionError("x"))
    assert RetryPolicy.is_retryable(TimeoutError("x"))
    assert RetryPolicy.is_retryable(InjectedFault("p", "drop"))
    assert not RetryPolicy.is_retryable(
        InjectedFault("p", "error", retryable=False))
    assert not RetryPolicy.is_retryable(ValueError("x"))
    assert not RetryPolicy.is_retryable(TypeError("x"))
    # a blown deadline must not be retried by an outer policy
    assert not RetryPolicy.is_retryable(DeadlineExceeded("x"))

    class FakeRpcError(grpc.RpcError):
        def __init__(self, c):
            self._c = c

        def code(self):
            return self._c

    assert RetryPolicy.is_retryable(
        FakeRpcError(grpc.StatusCode.UNAVAILABLE))
    assert RetryPolicy.is_retryable(
        FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED))
    assert not RetryPolicy.is_retryable(
        FakeRpcError(grpc.StatusCode.INVALID_ARGUMENT))
    assert not RetryPolicy.is_retryable(
        FakeRpcError(grpc.StatusCode.UNKNOWN))


def test_retry_run_retries_until_success():
    p = RetryPolicy(deadline=5.0, call_timeout=1.0, base_backoff=0.01,
                    max_backoff=0.02)
    calls = {"n": 0}
    retries = []

    def fn():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient %d" % calls["n"])
        return "ok"

    assert p.run(fn, on_retry=lambda e, a: retries.append(a)) == "ok"
    assert calls["n"] == 3
    assert retries == [1, 2]


def test_retry_run_deadline_exceeded_names_operation():
    p = RetryPolicy(deadline=0.2, base_backoff=0.05, max_backoff=0.05)
    with pytest.raises(DeadlineExceeded) as ei:
        p.run(lambda: (_ for _ in ()).throw(ConnectionError("down")),
              describe="GetVariable(127.0.0.1:9)")
    assert "GetVariable(127.0.0.1:9)" in str(ei.value)
    assert ei.value.attempts >= 1
    assert isinstance(ei.value.last_error, ConnectionError)


def test_retry_run_fatal_surfaces_immediately():
    p = RetryPolicy(deadline=10.0)
    with pytest.raises(ValueError):
        p.run(lambda: (_ for _ in ()).throw(ValueError("bug")))


def test_retry_run_attempt_cap():
    p = RetryPolicy(deadline=60.0, base_backoff=0.001, max_backoff=0.001,
                    max_attempts=3)
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise ConnectionError("x")

    with pytest.raises(DeadlineExceeded):
        p.run(fn)
    assert calls["n"] == 3


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------

def test_fault_spec_parse_and_limits():
    inj = FaultInjector("a:drop:1.0:2,b:delay:0.01,c:error:1.0")
    for _ in range(2):
        with pytest.raises(InjectedFault) as ei:
            inj.fire("a")
        assert ei.value.retryable
    inj.fire("a")  # limit=2 exhausted: no-op now
    t0 = time.time()
    inj.fire("b")
    assert time.time() - t0 >= 0.009
    with pytest.raises(InjectedFault) as ei:
        inj.fire("c")
    assert not ei.value.retryable
    assert inj.stats == {"a": 2, "b": 1, "c": 1}
    inj.fire("unknown_point")  # unconfigured points are free


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError):
        FaultInjector("send_grad:drop")          # missing value
    with pytest.raises(ValueError):
        FaultInjector("send_grad:explode:1.0")   # unknown action


def test_fault_spec_probability_zero_never_fires():
    inj = FaultInjector("a:drop:0.0")
    for _ in range(50):
        inj.fire("a")
    assert inj.stats == {}


# ---------------------------------------------------------------------------
# Wire format: (round, sender) packing + read-only decode regression
# ---------------------------------------------------------------------------

def test_pack_round_sender_roundtrip_and_legacy():
    assert _unpack_round_sender(_pack_round_sender(0, 0)) == (0, 0, 0)
    assert _unpack_round_sender(
        _pack_round_sender(2**23, 0xABCDEF, 0x3FFF)) \
        == (2**23, 0xABCDEF, 0x3FFF)
    # legacy plain extras (and negatives) decode as anonymous
    assert _unpack_round_sender(5) == (5, None, 0)
    assert _unpack_round_sender(0) == (0, None, 0)
    assert _unpack_round_sender(-2) == (-2, None, 0)


def test_dec_arr_view_is_readonly_mutation_fails_loudly():
    """Regression (satellite): _dec_tensor returns a zero-copy READ-ONLY
    view over the message buffer.  A consumer that accumulates in place
    without .copy() must fail loudly, not silently corrupt the buffer."""
    wire = bytes(_enc_tensor("g", np.arange(6, dtype=np.float32)))
    _, arr, _ = _dec_tensor(wire)
    assert not arr.flags.writeable
    with pytest.raises(ValueError):
        arr += 1.0
    # the sanctioned path: copy, then mutate
    safe = np.array(arr, copy=True)
    safe += 1.0
    np.testing.assert_allclose(safe, np.arange(6) + 1.0)


def test_apply_one_aggregates_readonly_views_in_place():
    """The pserver aggregation site accumulates in place — it must copy
    the first read-only wire view before += (satellite regression)."""
    applied = []
    scope = Scope()
    srv = VariableServer(scope, {"g": 0}, applied.append, fanin=2)
    for i, val in enumerate([2.0, 4.0]):
        wire = bytes(_enc_tensor(
            "g", np.full((3,), val, np.float32),
            _pack_round_sender(0, 100 + i)))
        _, arr, extra = _dec_tensor(wire)
        with srv._cv:
            srv._pending["g"][100 + i] = arr
            assert not arr.flags.writeable
            if i == 1:
                srv._apply_one("g")
    np.testing.assert_allclose(np.asarray(scope.find_var("g")),
                               np.full((3,), 3.0))
    assert applied == [0]


# ---------------------------------------------------------------------------
# Sender-dedup'd sync protocol (replay idempotence, legacy compat)
# ---------------------------------------------------------------------------

def _start_server(scope, fanin, **kw):
    applied = []
    srv = VariableServer(scope, {"g": 0}, applied.append, fanin=fanin,
                         **kw)
    port = srv.start("127.0.0.1:0")
    return srv, applied, "127.0.0.1:%d" % port


def test_replayed_round_is_idempotent():
    """A trainer that resends its round after a reconnect (replay cache)
    must not skew the sync mean: the server dedups by (round, sender)."""
    scope = Scope()
    srv, applied, ep = _start_server(scope, fanin=2)
    RPCClient.reset()
    a = RPCClient.instance()
    b = RPCClient()
    try:
        a.send_var(ep, "g", np.full((4,), 2.0, np.float32))
        # duplicate send + full replay — exactly what a retry does
        a.send_var(ep, "g", np.full((4,), 2.0, np.float32))
        a._replay_round(ep)
        b.send_var(ep, "g", np.full((4,), 4.0, np.float32))
        ts = [threading.Thread(target=c.send_barrier, args=([ep],))
              for c in (a, b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        got = a.get_var(ep, "g")
        # mean over TRAINERS (2), not over arrivals (4)
        np.testing.assert_allclose(np.asarray(got), np.full((4,), 3.0))
        assert applied == [0]
        assert srv._applied_round == 1
    finally:
        a.send_complete([ep])
        b.send_complete([ep])
        srv.wait()


def test_legacy_anonymous_sends_keep_append_semantics():
    """Un-flagged extras (old wire) must keep the historical behavior:
    every arrival is a distinct aggregation slot."""
    scope = Scope()
    srv, applied, ep = _start_server(scope, fanin=1)
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        for val in (1.0, 5.0):
            cli._call(ep, "SendVariable",
                      _enc_tensor("g", np.full((2,), val, np.float32), 0),
                      timeout=10.0)
        cli._call(ep, "SendBarrier", b"", timeout=10.0)  # legacy barrier
        with srv._cv:
            ok = srv._cv.wait_for(lambda: srv._applied_round >= 1,
                                  timeout=10.0)
        assert ok
        np.testing.assert_allclose(np.asarray(scope.find_var("g")),
                                   np.full((2,), 3.0))
        assert applied == [0]
    finally:
        cli.send_complete([ep])
        srv.wait()


def test_barrier_acks_only_after_durable_checkpoint(tmp_path):
    """SendBarrier returns only once the round is applied AND (with
    checkpoint_every_n=1) durably snapshotted — so a crash at ANY point
    either loses an un-acked round (trainers replay it) or nothing."""
    d = str(tmp_path / "shard")
    scope = Scope()
    srv, applied, ep = _start_server(scope, fanin=1, checkpoint_dir=d,
                                     checkpoint_every_n=1)
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        cli.send_var(ep, "g", np.full((3,), 6.0, np.float32))
        cli.send_barrier([ep])
        # the ack we just got implies the checkpoint is on disk
        assert os.path.exists(os.path.join(d, "_SUCCESS"))
        with open(os.path.join(d, "_SUCCESS")) as f:
            assert int(f.read()) == 1
        assert srv._durable_round == 1
    finally:
        cli.send_complete([ep])
        srv.wait()
    # a restarted server resumes at the applied round with the state
    scope2 = Scope()
    srv2 = VariableServer(scope2, {"g": 0}, lambda b: None, fanin=1,
                          checkpoint_dir=d)
    assert srv2._applied_round == 1
    np.testing.assert_allclose(np.asarray(scope2.find_var("g")),
                               np.full((3,), 6.0))


def test_replayed_barrier_not_acked_before_durable(tmp_path):
    """A RETRIED barrier for a round that is applied but whose
    checkpoint write is still in flight must wait for durability like
    the original did — acking it early would let trainers advance and
    wipe their replay caches while the round can still be lost to a
    crash (regression)."""
    d = str(tmp_path / "shard")
    scope = Scope()
    applied = []
    srv = VariableServer(scope, {"g": 0}, applied.append, fanin=1,
                         checkpoint_dir=d, checkpoint_every_n=1)
    ep = "127.0.0.1:%d" % srv.start("127.0.0.1:0")
    writing = threading.Event()
    orig_save = srv.save_shard

    def slow_save(dirname, snapshot=None):
        writing.set()
        time.sleep(0.6)
        orig_save(dirname, snapshot)

    srv.save_shard = slow_save
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        cli.send_var(ep, "g", np.ones((2,), np.float32))
        t = threading.Thread(target=cli.send_barrier, args=([ep],))
        t.start()
        assert writing.wait(5.0)
        # the round is applied (stale by round number) but NOT durable:
        # a replayed barrier must block until the write completes
        t0 = time.time()
        cli._call(ep, "SendBarrier", cli._barrier_payload(0),
                  timeout=10.0)
        assert srv._durable_round > 0      # ack implied durability
        assert time.time() - t0 >= 0.2     # it genuinely waited
        t.join(timeout=10.0)
        assert not t.is_alive()
    finally:
        cli.send_complete([ep])
        srv.wait()


def test_async_resend_of_applied_grad_is_dropped():
    """Async mode applies on arrival and clears pending, so round-replay
    dedup can't help a retried send: the per-sender send SEQUENCE must
    make a resend of an already-applied grad a no-op (regression: a
    lost reply + retry used to double-apply the optimizer step)."""
    scope = Scope()
    srv, applied, ep = _start_server(scope, fanin=1, sync_mode=False)
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        cli.send_var(ep, "g", np.full((2,), 1.0, np.float32))
        assert len(applied) == 1
        # the reply was "lost": the client replays the identical send
        cli._replay_round(ep)
        cli._replay_round(ep)
        assert len(applied) == 1          # dropped, not re-applied
        # a genuinely NEW send (fresh seq) applies again
        cli.send_var(ep, "g", np.full((2,), 2.0, np.float32))
        assert len(applied) == 2
    finally:
        cli.send_complete([ep])
        srv.wait()


def test_send_complete_after_lease_expiry_single_decrement():
    """A trainer counted out by the lease whose SendComplete arrives
    later (slow teardown) must not be subtracted twice — that would
    shut the server down under trainers still mid-round (regression)."""
    scope = Scope()
    srv, applied, ep = _start_server(scope, fanin=2, trainer_lease=0.4)
    RPCClient.reset()
    a = RPCClient.instance()
    a.retry = RetryPolicy(deadline=15.0, call_timeout=2.0)
    b = RPCClient()
    try:
        # round 0: both participate
        a.send_var(ep, "g", np.ones((2,), np.float32))
        b.send_var(ep, "g", np.ones((2,), np.float32))
        ts = [threading.Thread(target=c.send_barrier, args=([ep],))
              for c in (a, b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # round 1: B is silent -> lease expires it (alive 2 -> 1)
        a.send_var(ep, "g", np.ones((2,), np.float32))
        a.send_barrier([ep])
        assert srv._alive == 1
        # B's delayed complete must be a no-op, not a second decrement
        b.send_complete([ep])
        time.sleep(0.2)
        assert srv._alive == 1
        assert not srv._shutdown.is_set()
        # A can still run a full round
        a.send_var(ep, "g", np.full((2,), 3.0, np.float32))
        a.send_barrier([ep])
        assert srv._applied_round == 3
    finally:
        a.send_complete([ep])
        srv.wait()


def test_complete_then_silence_is_not_lease_expired():
    """The mirror ordering: a trainer that finished CLEANLY and went
    silent must not be lease-expired afterwards (second decrement)."""
    scope = Scope()
    srv, applied, ep = _start_server(scope, fanin=2, trainer_lease=0.4)
    RPCClient.reset()
    a = RPCClient.instance()
    a.retry = RetryPolicy(deadline=15.0, call_timeout=2.0)
    b = RPCClient()
    try:
        a.send_var(ep, "g", np.ones((2,), np.float32))
        b.send_var(ep, "g", np.ones((2,), np.float32))
        ts = [threading.Thread(target=c.send_barrier, args=([ep],))
              for c in (a, b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        b.send_complete([ep])             # B done: alive 2 -> 1
        assert srv._alive == 1
        # A trains on past B's lease window; the loop must not expire B
        for val in (2.0, 3.0):
            a.send_var(ep, "g", np.full((2,), val, np.float32))
            time.sleep(0.5)               # > lease of silence from B
            a.send_barrier([ep])
        assert srv._alive == 1
        assert not srv._shutdown.is_set()
    finally:
        a.send_complete([ep])
        srv.wait()


def test_restart_from_stale_checkpoint_fast_forwards_once():
    """Trainers ahead of a server restarted from an OLD checkpoint
    (checkpoint_every_n > 1): the replayed round must be applied ONCE
    with a jump to the trainers' round — not once per missing round
    (regression: multi-applied gradients + ~call_timeout stalls)."""
    scope = Scope()
    srv, applied, ep = _start_server(scope, fanin=1)
    RPCClient.reset()
    cli = RPCClient.instance()
    cli.retry = RetryPolicy(deadline=10.0, call_timeout=2.0)
    cli.step = 5   # trainer is at round 5; server recovered at round 0
    try:
        cli.send_var(ep, "g", np.full((2,), 4.0, np.float32))
        t0 = time.time()
        cli.send_barrier([ep])
        assert time.time() - t0 < 2.0     # no per-missing-round stalls
        assert applied == [0]             # exactly one optimizer apply
        assert srv._applied_round == 6    # jumped to the trainers' round
        got = cli.get_var(ep, "g")        # waits applied >= 6: no hang
        np.testing.assert_allclose(np.asarray(got), np.full((2,), 4.0))
    finally:
        cli.send_complete([ep])
        srv.wait()


# ---------------------------------------------------------------------------
# Watchdog: hangs become errors naming the missing peer
# ---------------------------------------------------------------------------

class _FakeOp:
    def __init__(self, attrs):
        self._attrs = attrs

    def attr(self, name, default=None):
        return self._attrs.get(name, default)


def test_watchdog_names_missing_peer_instead_of_hanging():
    """fanin=2, peer B completes round 0 then dies.  A's next barrier
    must fail with a WatchdogTimeout naming B — not hang forever."""
    from paddle_tpu.ops.distributed_ops import _send_barrier

    scope = Scope()
    srv, applied, ep = _start_server(scope, fanin=2)
    RPCClient.reset()
    a = RPCClient.instance()
    a.retry = RetryPolicy(deadline=2.0, call_timeout=0.5,
                          base_backoff=0.05, max_backoff=0.1)
    b = RPCClient()
    b.label = "trainerB@deadhost:1"
    try:
        # round 0: both participate (barriers block until applied)
        a.send_var(ep, "g", np.ones((2,), np.float32))
        b.send_var(ep, "g", np.ones((2,), np.float32))
        ts = [threading.Thread(target=c.send_barrier, args=([ep],))
              for c in (a, b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert srv._applied_round == 1
        # round 1: B is dead; A's barrier (via the host op) must time out
        a.send_var(ep, "g", np.ones((2,), np.float32))
        with pytest.raises(WatchdogTimeout) as ei:
            _send_barrier(None, _FakeOp({"endpoints": [ep]}), scope,
                          None)
        msg = str(ei.value)
        assert "trainerB@deadhost:1" in msg
        assert ep in msg
    finally:
        a.send_complete([ep])   # straggler path applies round 1
        b.send_complete([ep])
        srv.wait()


def test_trainer_lease_expires_dead_peer_and_round_completes():
    """Server-side lease (mirrors Master._check_timeouts): a trainer
    that dies mid-round is expired from the fanin after
    ``trainer_lease`` seconds of silence and the survivors' round
    applies with their contributions."""
    scope = Scope()
    srv, applied, ep = _start_server(scope, fanin=2, trainer_lease=0.6)
    RPCClient.reset()
    a = RPCClient.instance()
    a.retry = RetryPolicy(deadline=15.0, call_timeout=2.0)
    b = RPCClient()
    try:
        a.send_var(ep, "g", np.full((2,), 2.0, np.float32))
        b.send_var(ep, "g", np.full((2,), 4.0, np.float32))
        ts = [threading.Thread(target=c.send_barrier, args=([ep],))
              for c in (a, b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # round 1: only A shows up; B's lease must expire -> round
        # applies with A's grad alone and A's (blocking) barrier returns
        a.send_var(ep, "g", np.full((2,), 8.0, np.float32))
        t0 = time.time()
        a.send_barrier([ep])
        assert srv._applied_round == 2
        assert time.time() - t0 < 10.0
        np.testing.assert_allclose(np.asarray(scope.find_var("g")),
                                   np.full((2,), 8.0))
        assert srv._alive == 1
    finally:
        a.send_complete([ep])
        srv.wait()


# ---------------------------------------------------------------------------
# Endpoint re-resolution through discovery
# ---------------------------------------------------------------------------

def test_endpoint_resolver_follows_restarted_pserver(tmp_path):
    from paddle_tpu.distributed.discovery import EndpointRegistry

    reg = EndpointRegistry(str(tmp_path), ttl=30.0)
    reg.register("pserver", "127.0.0.1:6000", meta={"shard": "s0"},
                 heartbeat=False)
    reg.register("pserver", "127.0.0.1:6001", meta={"shard": "s1"},
                 heartbeat=False)
    resolver = EndpointResolver(reg, "pserver",
                                logical_eps=["127.0.0.1:6000",
                                             "127.0.0.1:6001"])
    assert resolver.resolve("127.0.0.1:6000") == "127.0.0.1:6000"
    # s0 crashes and comes back on a NEW port under the same shard id
    reg.unregister("pserver", "127.0.0.1:6000")
    reg.register("pserver", "127.0.0.1:7777", meta={"shard": "s0"},
                 heartbeat=False)
    assert resolver.resolve("127.0.0.1:6000") == "127.0.0.1:7777"
    assert resolver.resolve("127.0.0.1:6001") == "127.0.0.1:6001"
    # a shard with no live registration resolves to None (caller keeps
    # the logical endpoint and retries)
    reg.unregister("pserver", "127.0.0.1:6001")
    assert resolver.resolve("127.0.0.1:6001") is None


def test_rpc_client_reconnect_uses_resolver():
    cli = RPCClient()
    cli.set_resolver(lambda ep: "127.0.0.1:9999"
                     if ep == "127.0.0.1:1111" else ep)
    cli._reconnect("127.0.0.1:1111")
    assert cli._phys("127.0.0.1:1111") == "127.0.0.1:9999"
    # resolver returning the logical endpoint clears the redirect
    cli.set_resolver(lambda ep: ep)
    cli._reconnect("127.0.0.1:1111")
    assert cli._phys("127.0.0.1:1111") == "127.0.0.1:1111"


# ---------------------------------------------------------------------------
# Master: snapshot durability + client deadlines
# ---------------------------------------------------------------------------

def test_master_snapshot_survives_truncation(tmp_path):
    """A truncated live snapshot (torn disk, external cause) must not
    poison _recover: the .bak rotated by the previous _snapshot loads
    (satellite: tmp-file-then-rename + fallback)."""
    from paddle_tpu.distributed.master import Master

    snap = str(tmp_path / "master.json")
    m = Master(snapshot_path=snap, num_epochs=1)
    m.set_dataset(["a", "b", "c"])
    t = m.get_task()          # second snapshot -> rotates .bak
    m.task_finished(t.task_id)
    assert os.path.exists(snap + ".bak")
    with open(snap, "w") as f:
        f.write('{"todo": [{"task_id"')   # truncated JSON
    m2 = Master(snapshot_path=snap, num_epochs=1)
    c = m2.counts()
    # .bak holds the state one snapshot earlier: all three tasks live
    assert c["todo"] + c["pending"] + c["done"] == 3
    # both copies corrupt -> warn + empty start (at-least-once dispatch
    # makes a re-run safe; refusing to start is not)
    with open(snap + ".bak", "w") as f:
        f.write("not json")
    with pytest.warns(UserWarning):
        m3 = Master(snapshot_path=snap, num_epochs=1)
    assert m3.counts()["todo"] == 0


def test_master_client_deadline_instead_of_hang():
    """An RPC to a dead master fails with DeadlineExceeded after the
    retry budget — it must never hang forever."""
    from paddle_tpu.distributed.master import MasterClient

    dead = "127.0.0.1:%d" % _free_port()
    cli = MasterClient(dead, retry=RetryPolicy(
        deadline=1.0, call_timeout=0.3, base_backoff=0.05,
        max_backoff=0.1))
    t0 = time.time()
    with pytest.raises(DeadlineExceeded) as ei:
        cli.counts()
    assert time.time() - t0 < 10.0
    assert dead in str(ei.value)


def test_master_client_rides_through_injected_drops():
    from paddle_tpu.distributed.master import (Master, MasterClient,
                                               MasterServer)

    srv = MasterServer(Master(num_epochs=1))
    port = srv.start("127.0.0.1:0")
    inj = install_faults("master_rpc:drop:1.0:3")
    try:
        cli = MasterClient("127.0.0.1:%d" % port, retry=RetryPolicy(
            deadline=20.0, call_timeout=2.0, base_backoff=0.01,
            max_backoff=0.05))
        cli.set_dataset(["x"])
        t = cli.get_task()
        assert t.payload == "x"
        assert cli.task_finished(t.task_id)
        assert inj.stats["master_rpc"] == 3   # all three drops absorbed
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Slow: real processes, injected faults, SIGKILL + restart
# ---------------------------------------------------------------------------

def _spawn_ctx():
    # spawn children as PURE-CPU jax processes (see test_dist_train.py)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return mp.get_context("spawn")


def _baseline_to_queue(steps, kind, queue):
    queue.put(H.run_local_baseline(steps, kind))


def _collect(ctx, q, n_trainers, timeout=300):
    results = {}
    for _ in range(n_trainers):
        tid, losses = q.get(timeout=timeout)
        results[tid] = losses
    return results


def _baseline(ctx, steps, kind="softmax"):
    """The e2e parity reference.  Plain runs compare against the local
    single-process trajectory; with FLAGS_dist_compress exported
    (tools/fault_matrix.py 'compressed' preset) the reference is
    instead a FAULT-FREE distributed run under the same codec — the
    parity claim becomes 'faults + replays on the compressed wire are
    invisible to the math', which is exactly the idempotence guarantee
    compression must not break (a lossy codec can never match the
    uncompressed local baseline)."""
    if os.environ.get("FLAGS_dist_compress", "").strip():
        return _dist_reference(ctx, steps, kind)
    bq = ctx.Queue()
    bp = ctx.Process(target=_baseline_to_queue, args=(steps, kind, bq))
    bp.start()
    local = bq.get(timeout=240)
    bp.join(timeout=60)
    return local


def _dist_reference(ctx, steps, kind="softmax"):
    """A fault-free 2x2 distributed run (same topology as the e2e
    tests), fault injection explicitly CLEARED in every child."""
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(2)]
    pservers = ",".join(eps)
    clean = {"FLAGS_fastwire_port_offset": "0", "FLAGS_fault_spec": ""}
    ps_procs = [ctx.Process(target=H.run_pserver,
                            args=(ep, pservers, 2, kind, True, clean))
                for ep in eps]
    for p in ps_procs:
        p.start()
    q = ctx.Queue()
    tr_procs = [ctx.Process(target=H.run_trainer,
                            args=(tid, pservers, 2, steps, q, kind,
                                  True, clean))
                for tid in range(2)]
    for p in tr_procs:
        p.start()
    results = _collect(ctx, q, 2)
    for p in tr_procs + ps_procs:
        p.join(timeout=60)
        if p.is_alive():
            p.terminate()
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)
    return results[0]


def _merged_spec(base):
    """Combine the test's own fault spec with an externally exported
    FLAGS_fault_spec (tools/fault_matrix.py presets), so the matrix
    runner genuinely varies the stress level of these e2e tests."""
    extra = os.environ.get("FLAGS_fault_spec", "").strip()
    return ",".join(s for s in (base, extra) if s)


@pytest.mark.slow
def test_dist_train_survives_injected_faults():
    """Sync-SGD under dropped sends, dropped gets, delayed gets, and
    dropped barriers must converge to EXACTLY the fault-free losses:
    the retry + (round, sender)-dedup'd replay protocol makes every
    recovery path invisible to the math."""
    ctx = _spawn_ctx()
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(2)]
    pservers = ",".join(eps)
    n_trainers, steps = 2, 8
    ps_env = {"FLAGS_fastwire_port_offset": "0"}
    tr_env = {
        "FLAGS_fastwire_port_offset": "0",
        "FLAGS_fault_spec": _merged_spec(
            "send_grad:drop:0.3:8,get_param:drop:0.3:8,"
            "get_param:delay:0.05:6,send_barrier:drop:0.5:4"),
        "FLAGS_rpc_deadline": "240",
        "FLAGS_rpc_call_timeout": "10",
        "FLAGS_rpc_retry_backoff": "0.05",
    }
    ps_procs = [ctx.Process(target=H.run_pserver,
                            args=(ep, pservers, n_trainers, "softmax",
                                  True, ps_env))
                for ep in eps]
    for p in ps_procs:
        p.start()
    q = ctx.Queue()
    tr_procs = [ctx.Process(target=H.run_trainer,
                            args=(tid, pservers, n_trainers, steps, q,
                                  "softmax", True, tr_env))
                for tid in range(n_trainers)]
    for p in tr_procs:
        p.start()
    results = _collect(ctx, q, n_trainers)
    for p in tr_procs:
        p.join(timeout=60)
    for p in ps_procs:
        p.join(timeout=60)
        if p.is_alive():
            p.terminate()
            pytest.fail("pserver did not shut down after SendComplete")
    local = _baseline(ctx, steps)
    for tid in range(n_trainers):
        np.testing.assert_allclose(results[tid], local, rtol=1e-4,
                                   atol=1e-5)
    assert local[-1] < local[0] * 0.8   # and it actually learned


@pytest.mark.slow
def test_pserver_sigkill_restart_mid_training_recovers(tmp_path):
    """One pserver is SIGKILLed mid-training and restarted on the same
    endpoint with its checkpoint dir: durable-ack checkpoints (every
    round) + trainer-side round replay make the final losses match the
    fault-free run exactly."""
    ctx = _spawn_ctx()
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(2)]
    pservers = ",".join(eps)
    n_trainers, steps = 2, 10
    ckpt_root = str(tmp_path / "shards")
    ps_env = {
        "FLAGS_fastwire_port_offset": "0",
        "FLAGS_pserver_checkpoint_root": ckpt_root,
        "FLAGS_pserver_checkpoint_every_n": "1",
    }
    tr_env = {
        "FLAGS_fastwire_port_offset": "0",
        # pace the rounds so the kill lands mid-training
        "FLAGS_fault_spec": _merged_spec("get_param:delay:0.1"),
        "FLAGS_rpc_deadline": "240",
        "FLAGS_rpc_call_timeout": "5",
    }
    ps_procs = [ctx.Process(target=H.run_pserver,
                            args=(ep, pservers, n_trainers, "softmax",
                                  True, ps_env))
                for ep in eps]
    for p in ps_procs:
        p.start()
    q = ctx.Queue()
    tr_procs = [ctx.Process(target=H.run_trainer,
                            args=(tid, pservers, n_trainers, steps, q,
                                  "softmax", True, tr_env))
                for tid in range(n_trainers)]
    for p in tr_procs:
        p.start()

    time.sleep(2.5)                 # mid-training (>=0.2s per round)
    assert q.empty(), "training finished before the kill landed"
    ps_procs[0].kill()              # SIGKILL: no cleanup, no goodbyes
    ps_procs[0].join(timeout=30)
    restarted = ctx.Process(target=H.run_pserver,
                            args=(eps[0], pservers, n_trainers,
                                  "softmax", True, ps_env))
    restarted.start()

    results = _collect(ctx, q, n_trainers)
    for p in tr_procs:
        p.join(timeout=60)
    for p in (ps_procs[1], restarted):
        p.join(timeout=60)
        if p.is_alive():
            p.terminate()
    local = _baseline(ctx, steps)
    for tid in range(n_trainers):
        np.testing.assert_allclose(results[tid], local, rtol=1e-4,
                                   atol=1e-5)
