"""Parity modules: evaluator / lod_tensor / average / recordio_writer /
default_scope_funcs, and the long-tail dataset adapters (reference
python/paddle/fluid/{evaluator,lod_tensor,average,recordio_writer,
default_scope_funcs}.py, python/paddle/dataset/)."""
import itertools
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import dataset


def test_weighted_average():
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        avg = fluid.average.WeightedAverage()
    avg.add(value=2.0, weight=1)
    avg.add(value=4.0, weight=3)
    assert abs(avg.eval() - 3.5) < 1e-9
    avg.reset()
    with pytest.raises(ValueError):
        avg.eval()


def test_create_lod_tensor():
    lt = fluid.create_lod_tensor(np.arange(10).reshape(5, 2).astype(
        "float32"), [[2, 3]], fluid.CPUPlace())
    assert lt.lod == [[0, 2, 5]]
    assert lt.shape == (5, 2)
    # list-of-sequences form
    lt2 = fluid.create_lod_tensor([[1, 2], [3, 4, 5]], [[2, 3]],
                                  fluid.CPUPlace())
    assert lt2.shape == (5, 1) and lt2.lod == [[0, 2, 5]]
    # invalid lod rejected
    with pytest.raises(AssertionError):
        fluid.create_lod_tensor(np.zeros((5, 2), "float32"), [[2, 2]],
                                fluid.CPUPlace())
    # level-2: sentence counts over word counts
    rand = fluid.create_random_int_lodtensor(
        [[2, 1], [3, 2, 4]], base_shape=[1], place=fluid.CPUPlace(),
        low=0, high=9)
    assert rand.shape == (9, 1)
    assert rand.lod == [[0, 2, 3], [0, 3, 5, 9]]
    assert np.asarray(rand).max() <= 9


def test_lod_tensor_feeds_executor(prog_scope, exe):
    """create_lod_tensor output is feedable wherever a ragged batch is."""
    main, startup, scope = prog_scope
    words = fluid.layers.data(name="w", shape=[1], dtype="int64",
                              lod_level=1)
    emb = fluid.layers.embedding(words, size=[20, 4])
    pooled = fluid.layers.sequence_pool(emb, "sum")
    out = fluid.layers.reduce_sum(pooled)
    exe.run(startup)
    lt = fluid.create_lod_tensor([[1, 2], [3, 4, 5]], [[2, 3]],
                                 fluid.CPUPlace())
    v, = exe.run(main, feed={"w": lt}, fetch_list=[out])
    assert np.isfinite(np.asarray(v)).all()


def test_chunk_evaluator_streaming(prog_scope, exe):
    """Evaluator states accumulate across runs; F1 matches a
    hand-accumulated computation over the same batches."""
    main, startup, scope = prog_scope
    pred = fluid.layers.data(name="pred", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64",
                              lod_level=1)
    ev = fluid.evaluator.ChunkEvaluator(input=pred, label=label,
                                        chunk_scheme="IOB",
                                        num_chunk_types=2)
    exe.run(startup)
    ev.reset(exe)
    feeder = fluid.DataFeeder([pred, label], program=main)
    rng = np.random.RandomState(0)
    batches = []
    for _ in range(3):
        batch = []
        for _ in range(4):
            L = int(rng.randint(3, 8))
            p = rng.randint(0, 5, L).tolist()
            t = rng.randint(0, 5, L).tolist()
            batch.append((p, t))
        batches.append(batch)
    for batch in batches:
        exe.run(main, feed=feeder.feed(batch), fetch_list=[])
    precision, recall, f1 = ev.eval(exe)
    assert 0.0 <= float(precision[0]) <= 1.0
    assert 0.0 <= float(f1[0]) <= 1.0

    # independent recomputation through the op's own batch counts
    main2 = fluid.Program()
    with fluid.program_guard(main2):
        p2 = fluid.layers.data(name="pred", shape=[1], dtype="int64",
                               lod_level=1)
        l2 = fluid.layers.data(name="label", shape=[1], dtype="int64",
                               lod_level=1)
        _, _, _, ni, nl, nc = fluid.layers.chunk_eval(
            input=p2, label=l2, chunk_scheme="IOB", num_chunk_types=2)
    tot = np.zeros(3)
    feeder2 = fluid.DataFeeder([p2, l2], program=main2)
    for batch in batches:
        vals = exe.run(main2, feed=feeder2.feed(batch),
                       fetch_list=[ni, nl, nc])
        tot += [float(np.asarray(v).ravel()[0]) for v in vals]
    want_p = tot[2] / tot[0] if tot[0] else 0.0
    want_r = tot[2] / tot[1] if tot[1] else 0.0
    assert abs(float(precision[0]) - want_p) < 1e-6
    assert abs(float(recall[0]) - want_r) < 1e-6


def test_edit_distance_evaluator(prog_scope, exe):
    main, startup, scope = prog_scope
    hyp = fluid.layers.data(name="hyp", shape=[1], dtype="int64",
                            lod_level=1)
    ref = fluid.layers.data(name="ref", shape=[1], dtype="int64",
                            lod_level=1)
    ev = fluid.evaluator.EditDistance(input=hyp, label=ref)
    exe.run(startup)
    ev.reset(exe)
    feeder = fluid.DataFeeder([hyp, ref], program=main)
    # distances: ("ab" vs "ab")=0, ("abc" vs "axc")=1 -> avg 0.5 norm’d
    exe.run(main, feed=feeder.feed([([1, 2], [1, 2]),
                                    ([1, 2, 3], [1, 9, 3])]),
            fetch_list=[])
    dist, err = ev.eval(exe)
    assert abs(float(err[0]) - 0.5) < 1e-6  # one of two seqs wrong
    assert float(dist[0]) > 0.0


def test_recordio_writer_roundtrip(tmp_path):
    import pickle
    from paddle_tpu import recordio

    def reader():
        for i in range(7):
            yield (np.full((2,), i, np.float32), i)

    path = os.path.join(str(tmp_path), "data.recordio")
    n = fluid.recordio_writer.convert_reader_to_recordio_file(path, reader)
    assert n == 7
    got = [pickle.loads(r) for r in recordio.Scanner(path)]
    assert len(got) == 7
    assert got[3][1] == 3 and np.allclose(got[3][0], 3.0)

    counts = fluid.recordio_writer.convert_reader_to_recordio_files(
        os.path.join(str(tmp_path), "part.recordio"), 3, reader)
    assert counts == [3, 3, 1]


def test_default_scope_funcs():
    dsf = fluid.default_scope_funcs
    base = dsf.get_cur_scope()
    dsf.enter_local_scope()
    assert dsf.get_cur_scope() is not base
    dsf.get_cur_scope().set("x", 42)
    assert np.asarray(dsf.find_var("x")) == 42
    dsf.leave_local_scope()
    assert dsf.get_cur_scope() is base

    out = dsf.scoped_function(lambda: 7)
    assert out == 7


def test_long_tail_datasets():
    # wmt16: reader + dict
    d = dataset.wmt16.get_dict("en", 50)
    assert d["<s>"] == 0 and len(d) == 50
    s = list(itertools.islice(dataset.wmt16.train(50, 50)(), 3))
    assert all(len(x) == 3 for x in s)
    src, trg_in, trg_next = s[0]
    assert trg_in[0] == 0 and trg_next[-1] == 1
    # sentiment: word dict + split sizes
    wd = dataset.sentiment.get_word_dict()
    assert len(wd) > 100 and isinstance(wd[0], tuple)
    tr = list(dataset.sentiment.train()())
    te = list(dataset.sentiment.test()())
    assert len(tr) == dataset.sentiment.NUM_TRAINING_INSTANCES
    assert len(te) == (dataset.sentiment.NUM_TOTAL_INSTANCES
                       - dataset.sentiment.NUM_TRAINING_INSTANCES)
    # mq2007: three ranking views
    lbl, a, b = next(dataset.mq2007.train(format="pairwise")())
    assert a.shape == (dataset.mq2007.FEATURE_DIM,) and lbl[0] == 1.0
    rel, fv = next(dataset.mq2007.train(format="listwise")())
    assert fv.shape[1] == dataset.mq2007.FEATURE_DIM
    assert (np.diff(rel) <= 0).all()  # sorted by descending relevance
    # voc2012: image/mask pair agreement
    img, mask = next(dataset.voc2012.train()())
    assert img.shape[:2] == mask.shape and img.dtype == np.uint8
    assert mask.max() > 0
    # image utils: full transform pipeline
    chw = dataset.image.simple_transform(img, 64, 48, is_train=True)
    assert chw.shape == (3, 48, 48) and chw.dtype == np.float32


def test_generated_layers_track_registry():
    """fluid.layers.ops generates a front-end name for EVERY registered
    pure X->Out op (reference layer_function_generator.py role): no op
    with that signature may lack a layer function."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.layers import ops as lops

    for op in lops.unary_op_types():
        assert hasattr(fluid.layers, op), op
    # spot-check newly generated names end-to-end
    from paddle_tpu.core.scope import Scope
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(Scope()), \
            fluid.program_guard(main, startup), \
            fluid.unique_name.guard():
        x = fluid.layers.data(name="gx", shape=[4], dtype="float32")
        outs = [fluid.layers.l1_norm(x),
                fluid.layers.squared_l2_norm(x),
                fluid.layers.fill_zeros_like(x),
                fluid.layers.log_softmax(x),
                fluid.layers.arg_max(x, axis=1)]
        exe = fluid.Executor(fluid.CPUPlace())
        xv = np.asarray([[1.0, -2.0, 3.0, -4.0]], np.float32)
        rs = exe.run(main, feed={"gx": xv}, fetch_list=outs)
    np.testing.assert_allclose(float(np.ravel(rs[0])[0]), 10.0, atol=1e-5)
    np.testing.assert_allclose(float(np.ravel(rs[1])[0]), 30.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(rs[2]), np.zeros((1, 4)))
    assert int(np.ravel(rs[4])[0]) == 2
