"""Pallas fused conv-stage kernel (kernels/conv_fused.py) and the
fused_conv2d_bn_act op: interpret-mode kernel parity vs the XLA path,
and op-level forward/grad parity vs the unfused conv2d+batch_norm+relu
chain (the NCHW baseline the layout transpiler replaces)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.kernels import conv_fused


@pytest.mark.parametrize("shape", [
    # (h, w, ci, co, k, stride, pad) — the ResNet stage shapes in
    # miniature: 3x3 s1 residual stage, 3x3 s2 downsample, 7x7 s2 stem,
    # 1x1 s1 and 1x1 s2 shortcut
    (8, 8, 4, 8, 3, 1, 1),
    (8, 8, 4, 8, 3, 2, 1),
    (12, 12, 3, 8, 7, 2, 3),
    (8, 8, 8, 16, 1, 1, 0),
    (8, 8, 8, 16, 1, 2, 0),
])
def test_kernel_matches_xla_with_stats(shape):
    h, w, ci, co, k, s, p = shape
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, h, w, ci), jnp.float32)
    wt = jnp.asarray(rng.randn(k, k, ci, co), jnp.float32) * 0.2
    y, su, ss = conv_fused.conv2d_nhwc(x, wt, (s, s), (p, p), stats=True,
                                       interpret=True)
    ref = np.asarray(conv_fused.conv_nhwc_xla(x, wt, (s, s), (p, p)))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(su), ref.reshape(-1, co).sum(0),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(
        np.asarray(ss), np.square(ref).reshape(-1, co).sum(0),
        rtol=1e-3, atol=1e-3)


def test_kernel_fused_epilogue_matches_reference():
    """Test-mode full fusion: conv + BN affine + residual + relu in one
    kernel vs the XLA reference."""
    rng = np.random.RandomState(1)
    h, ci, co, k, s, p = 8, 4, 8, 3, 1, 1
    x = jnp.asarray(rng.randn(2, h, h, ci), jnp.float32)
    wt = jnp.asarray(rng.randn(k, k, ci, co), jnp.float32) * 0.2
    scale = jnp.asarray(rng.rand(co) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(co), jnp.float32)
    mean = jnp.asarray(rng.randn(co) * 0.1, jnp.float32)
    var = jnp.asarray(rng.rand(co) + 0.5, jnp.float32)
    res = jnp.asarray(rng.randn(2, h, h, co), jnp.float32)
    inv = jax.lax.rsqrt(var + 1e-5)
    a, b = scale * inv, bias - mean * scale * inv
    got = conv_fused.conv2d_nhwc(x, wt, (s, s), (p, p), affine=(a, b),
                                 residual=res, act="relu",
                                 interpret=True)
    want = conv_fused.fused_conv_bn_act_reference(
        x, wt, scale, bias, mean, var, strides=(s, s), paddings=(p, p),
        eps=1e-5, act="relu", residual=res)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def _build_unfused(img, ci, co, k, s, p, act, with_residual):
    """conv2d -> batch_norm (-> add residual) (-> relu), NCHW builder."""
    data = fluid.layers.data(name="x", shape=[ci, img, img],
                             dtype="float32")
    conv = fluid.layers.conv2d(input=data, num_filters=co, filter_size=k,
                               stride=s, padding=p, act=None,
                               bias_attr=False)
    out = fluid.layers.batch_norm(input=conv,
                                  act=None if with_residual else act)
    if with_residual:
        sc = fluid.layers.data(name="r",
                               shape=[co, conv.shape[2], conv.shape[3]],
                               dtype="float32")
        sc.stop_gradient = False
        out = fluid.layers.elementwise_add(x=sc, y=out, act=act)
    loss = fluid.layers.reduce_sum(out)
    return data, loss


@pytest.mark.parametrize("act,with_residual", [
    (None, False), ("relu", False), ("relu", True)])
def test_fused_op_training_parity(act, with_residual):
    """The transpiled (NHWC + fused_conv2d_bn_act) program must match
    the NCHW conv2d+batch_norm(+add)(+relu) chain: loss AND parameter
    gradients, over several SGD steps (running BN stats included)."""
    img, ci, co, k, s, p = 8, 4, 8, 3, 1, 1

    def run(transpile, params=None, steps=3):
        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    data, loss = _build_unfused(img, ci, co, k, s, p,
                                                act, with_residual)
                    if transpile:
                        fluid.transpiler.LayoutTranspiler().transpile(
                            main, startup_program=startup,
                            data_format="NHWC", fuse_stages=True)
                    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            if params is not None:
                for n, v in params.items():
                    cur = np.asarray(scope.find_var(n))
                    if v.shape != cur.shape and v.ndim == 4:
                        v = np.ascontiguousarray(
                            np.transpose(v, (2, 3, 1, 0)))
                    scope.set(n, v.astype(cur.dtype))
            snap = {n: np.asarray(scope.find_var(n))
                    for n in scope.local_var_names()}
            rng = np.random.RandomState(3)
            feed = {"x": rng.randn(2, ci, img, img).astype(np.float32)}
            if with_residual:
                feed["r"] = rng.randn(2, co, img, img).astype(np.float32)
            losses = []
            for _ in range(steps):
                l, = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
            post = {n: np.asarray(scope.find_var(n))
                    for n in scope.local_var_names()}
        ops = [o.type for o in main.desc.blocks[0].ops]
        return losses, snap, post, ops

    base_losses, params, base_post, base_ops = run(False)
    losses, _, post, ops = run(True, params=dict(params))
    assert "fused_conv2d_bn_act" in ops
    assert "conv2d" not in ops and "batch_norm" not in ops
    assert "fused_conv2d_bn_act_grad" in ops
    np.testing.assert_allclose(base_losses, losses, rtol=1e-4, atol=1e-4)
    # post-step parameters: covers Filter/Scale/Bias grads and the
    # running-stat updates end to end
    for n, v in base_post.items():
        w = post.get(n)
        if w is None or v.dtype.kind != "f":
            continue
        if v.shape != w.shape and v.ndim == 4:
            v = np.transpose(v, (2, 3, 1, 0))
        if v.shape == w.shape:
            np.testing.assert_allclose(v, w, rtol=1e-3, atol=1e-4,
                                       err_msg=n)


def test_fused_op_test_mode_runs_without_convout():
    """is_test: the fully-fused path writes no ConvOut; the program
    still runs (nothing reads it in an inference program)."""
    img, ci, co = 8, 4, 8
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                data = fluid.layers.data(name="x", shape=[ci, img, img],
                                         dtype="float32")
                conv = fluid.layers.conv2d(input=data, num_filters=co,
                                           filter_size=3, padding=1,
                                           act=None, bias_attr=False)
                out = fluid.layers.batch_norm(input=conv, act="relu",
                                              is_test=True)
                mean = fluid.layers.mean(out)
                fluid.transpiler.LayoutTranspiler().transpile(
                    main, startup_program=startup, data_format="NHWC",
                    fuse_stages=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        x = np.random.RandomState(0).randn(2, ci, img, img).astype(
            np.float32)
        m, = exe.run(main, feed={"x": x}, fetch_list=[mean])
        assert np.isfinite(np.asarray(m)).all()
    assert any(o.type == "fused_conv2d_bn_act"
               for o in main.desc.blocks[0].ops)
