"""Inference predictor API (reference paddle/contrib/inference/
paddle_inference_api.h: PaddleTensor/NativeConfig/AnalysisConfig/
create_paddle_predictor, Run/Clone contract)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.inference import (AnalysisConfig, NativeConfig,
                                  PaddleTensor, create_paddle_predictor)

layers = fluid.layers


def _save_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                img = layers.data(name="img", shape=[3, 8, 8],
                                  dtype="float32")
                conv = layers.conv2d(img, num_filters=4, filter_size=3,
                                     padding=1, bias_attr=True)
                bn = layers.batch_norm(conv, is_test=True)
                out = layers.fc(layers.relu(bn), size=5, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d = str(tmp_path / "model")
        fluid.io.save_inference_model(d, ["img"], [out], exe,
                                      main_program=main)
        xv = np.random.RandomState(0).rand(2, 3, 8, 8).astype(
            np.float32)
        want, = exe.run(main, feed={"img": xv}, fetch_list=[out])
    return d, xv, np.asarray(want)


def test_native_predictor_matches_executor(tmp_path):
    d, xv, want = _save_model(tmp_path)
    pred = create_paddle_predictor(NativeConfig(model_dir=d))
    outs = pred.run([PaddleTensor(name="img", data=xv)])
    assert len(outs) == 1
    np.testing.assert_allclose(outs[0].data, want, rtol=1e-5,
                               atol=1e-6)
    # dict-feed form and positional (unnamed) form
    outs2 = pred.run({"img": xv})
    np.testing.assert_allclose(outs2[0].data, want, rtol=1e-5,
                               atol=1e-6)
    outs3 = pred.run([PaddleTensor(data=xv)])
    np.testing.assert_allclose(outs3[0].data, want, rtol=1e-5,
                               atol=1e-6)


def test_analysis_predictor_folds_bn(tmp_path):
    d, xv, want = _save_model(tmp_path)
    pred = create_paddle_predictor(
        AnalysisConfig(model_dir=d, fold_batch_norm=True))
    n_bn = sum(1 for op in pred.program.desc.blocks[0].ops
               if op.type == "batch_norm")
    assert n_bn == 0  # folded away
    outs = pred.run({"img": xv})
    np.testing.assert_allclose(outs[0].data, want, rtol=1e-4,
                               atol=1e-5)


def test_predictor_clone_shares_weights(tmp_path):
    d, xv, want = _save_model(tmp_path)
    pred = create_paddle_predictor(NativeConfig(model_dir=d))
    clone = pred.clone()
    assert clone.scope is pred.scope  # weights shared
    np.testing.assert_allclose(clone.run({"img": xv})[0].data, want,
                               rtol=1e-5, atol=1e-6)
    # missing feed errors clearly
    import pytest
    with pytest.raises(ValueError, match="missing feeds"):
        pred.run({})
