"""Copy-on-write prefix KV reuse (ISSUE 19 tentpole a): the radix
prefix index over the refcounted BlockPool — hit/miss/partial-block
boundary lookups, COW write isolation, refcount-ordered LRU eviction,
bit-identical tokens with the cache on vs off, and the interplay with
pool-exhaustion preemption.  The refcount/double-free sanitizer cases
and the lifetime checker's shared-block rule ride along."""
import numpy as np
import pytest

from paddle_tpu.core import sanitizer as san
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.observability import metrics
from paddle_tpu.serving import (BlockPool, GenerativeEngine,
                                InferenceServer, tiny_lm)

CFG_KW = dict(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
              block_size=8, max_blocks=8, max_batch=4)


class _Req:
    """The two attributes PrefixCache.acquire contracts on."""

    def __init__(self, prompt):
        self.prompt = list(prompt)
        self.blocks = None
        self.cached_len = 0


def _engine(**kw):
    cfg, params = tiny_lm(7, **CFG_KW)
    kw.setdefault("kv_blocks", 32)
    kw.setdefault("warm", False)
    return GenerativeEngine(cfg, params, prefix_cache=True, **kw)


def _prompts(seed, n, lo=3, hi=15):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, size=rng.randint(lo, hi)).tolist()
            for _ in range(n)]


# ------------------------------------------------------- radix index

def test_radix_hit_miss_partial_boundary():
    """Lookup semantics at block granularity: a cold prompt misses; a
    re-walked prompt hits its full chunks; the final prompt token is
    NEVER served from cache (the suffix prefill must compute
    something); a divergent-suffix prompt gets the shared full chunks
    plus a COW tail capped at the divergence point."""
    eng = _engine()
    try:
        idx = eng.prefix_cache
        bs = eng.config.block_size
        prompt = list(np.random.RandomState(0).randint(0, 64, 20))

        # cold: miss
        assert idx.probe(prompt) == (0, 0)
        a = _Req(prompt)
        assert idx.acquire(a) and a.cached_len == 0
        assert len(a.blocks) == eng.pool.blocks_for(20)
        idx.insert(a)
        assert idx.nodes == 2          # 20 // 8 full chunks

        # exact re-walk: both full chunks hit, positions 16..19 do not
        assert idx.probe(prompt) == (2, 16)
        # a prompt that IS exactly the indexed chunks: the full-chunk
        # walk stops a chunk early (position n-1 stays un-cached by
        # contract) and the final chunk downgrades to a COW tail of
        # bs-1 tokens
        assert idx.probe(prompt[:2 * bs]) == (2, 2 * bs - 1)
        # unrelated prompt: miss
        assert idx.probe([63] * 20) == (0, 0)

        # partial tail: shares chunk 0 whole, diverges inside chunk 1
        b_prompt = prompt[:12] + [(prompt[12] + 1) % 64]
        shared_n, cached = idx.probe(b_prompt)
        assert shared_n == 2 and cached == 12   # 8 full + 4 COW tail

        b = _Req(b_prompt)
        cow0 = metrics.counter("serve_kv_cow_copies_total").value
        assert idx.acquire(b) and b.cached_len == 12
        assert metrics.counter(
            "serve_kv_cow_copies_total").value == cow0 + 1
        # the shared full chunk is the SAME block; the COW tail is a
        # private copy, not A's chunk-1 block
        assert b.blocks[0] == a.blocks[0]
        assert b.blocks[1] != a.blocks[1]
        assert eng.pool.ref(a.blocks[0]) == 2
        assert eng.pool.ref(b.blocks[1]) == 1
        eng.pool.free(a.blocks)
        eng.pool.free(b.blocks)
    finally:
        eng.close()


def test_cow_write_isolation():
    """The COW copy carries the shared prefix's device pages: after
    the copy the two sequences' K/V diverge without either seeing the
    other's writes — checked at page level via export_blocks."""
    eng = _engine()
    try:
        idx = eng.prefix_cache
        prompt = list(range(16))
        a = _Req(prompt)
        assert idx.acquire(a)
        # write recognizable K/V into A's pages via a real prefill
        eng.prefill_tokens(a.prompt, a.blocks)
        idx.insert(a)

        b = _Req(prompt[:12] + [63])
        assert idx.acquire(b)
        assert b.blocks[1] != a.blocks[1]
        # COW copied A's chunk-1 pages into B's private block...
        k_a, v_a, _ = eng.export_blocks([a.blocks[1]])
        k_b, v_b, _ = eng.export_blocks([b.blocks[1]])
        np.testing.assert_array_equal(k_a, k_b)
        np.testing.assert_array_equal(v_a, v_b)
        # ...and a write into B's block leaves A's pages untouched
        before = eng.export_blocks([a.blocks[1]])[0]
        eng._prefill_suffix(b.prompt, b.blocks, 12)
        after = eng.export_blocks([a.blocks[1]])[0]
        np.testing.assert_array_equal(before, after)
        eng.pool.free(a.blocks)
        eng.pool.free(b.blocks)
    finally:
        eng.close()


# ------------------------------------------------- refcount eviction

def test_refcount_eviction_order():
    """Released cacheable blocks PARK in the LRU (used -> cached, not
    freed); allocation pressure reclaims oldest-parked first, and a
    revived (shared) block re-parks at the recent end."""
    evicted = []
    pool = BlockPool(6, 8)             # 5 usable
    pool.set_evict_callback(lambda b: evicted.append(b) or ())
    try:
        a = pool.alloc(3)
        pool.set_cacheable(a)
        pool.free(a)                   # park a0, a1, a2 (oldest first)
        assert pool.used_blocks == 0 and pool.cached_blocks == 3
        assert metrics.gauge("serve_kv_blocks_cached").value >= 3

        # revive the oldest, re-park it: now a1 is LRU-oldest
        assert pool.share([a[0]])
        assert pool.ref(a[0]) == 1
        pool.free([a[0]])
        assert pool.cached_blocks == 3

        # 2 free blocks remain; asking for 4 reclaims 2 parked, LRU
        # order: a1 then a2, never the recently-parked a0
        got = pool.alloc(4)
        assert got is not None
        assert evicted == [a[1], a[2]]
        assert pool.cached_blocks == 1
        pool.free(got)
    finally:
        pool.close()


def test_shared_block_counts_once_and_decref_is_not_free():
    pool = BlockPool(6, 8)
    try:
        used0 = pool.used_blocks
        blk = pool.alloc(1)
        assert pool.share(blk) and pool.ref(blk[0]) == 2
        # refcount semantics: shared counts once in used
        assert pool.used_blocks == used0 + 1
        assert metrics.gauge("serve_kv_blocks_shared").value >= 1
        pool.free(blk)                 # decref to 1: NOT a free
        assert pool.used_blocks == used0 + 1
        assert pool.ref(blk[0]) == 1
        pool.free(blk)                 # terminal decref
        assert pool.used_blocks == used0
    finally:
        pool.close()


def test_double_free_trips_buffers_sanitizer():
    prev = FLAGS.sanitizer
    FLAGS.sanitizer = "buffers"
    try:
        pool = BlockPool(6, 8)
        blk = pool.alloc(1)
        pool.share(blk)
        pool.free(blk)
        pool.free(blk)                 # terminal decref: fine
        with pytest.raises(san.BufferLifetimeError, match="decref"):
            pool.free(blk)             # one decref too many
        pool.close()
    finally:
        FLAGS.sanitizer = prev


def test_lifetime_checker_covers_shared_blocks():
    from paddle_tpu.analysis import lifetime as lt
    from paddle_tpu.analysis.diagnostics import Severity

    diags = lt.check_serving_fetches(
        ["tokens", "shared_prefix"], [], site="tenant g",
        shared_state=["shared_prefix"])
    assert len(diags) == 1 and diags[0].var == "shared_prefix"
    assert diags[0].severity == Severity.ERROR
    assert "copy-on-write" in diags[0].message
    # donated classification wins over shared (one report per var)
    diags = lt.check_serving_fetches(
        ["kv_pages"], ["kv_pages"], shared_state=["kv_pages"])
    assert len(diags) == 1 and "donated" in diags[0].message


# --------------------------------------------------------------- e2e

def test_bit_identical_tokens_cache_on_vs_off():
    """THE correctness contract: greedy tokens must be bit-identical
    with the prefix cache on vs off, and the cached run must actually
    share (hits > 0, cached tokens > 0)."""
    cfg, params = tiny_lm(7, **CFG_KW)
    shared = list(np.random.RandomState(3).randint(0, 64, 17))
    prompts = [shared + [t] for t in (1, 2, 3)] + [shared[:10] + [5]]

    hits = []

    def run(on):
        metrics.zero_all()
        with InferenceServer() as srv:
            srv.load_generative("g", cfg, params, kv_blocks=64,
                                warm=False, prefix_cache=on)
            toks = [srv.generate("g", p, max_new_tokens=12).result(300)
                    ["tokens"] for p in prompts]
            # the hits gauge is recomputed from LIVE pools — read it
            # before unload retires this tenant's pool
            hits.append(metrics.gauge("serve_kv_prefix_hits").value)
        return toks

    off = run(False)
    on = run(True)
    assert on == off, "prefix cache changed greedy tokens"
    assert hits == [0, 3], hits     # 3 warm lookups shared blocks
    assert metrics.counter(
        "serve_prefix_tokens_cached_total").value > 0


def test_pool_exhaustion_preemption_with_prefix_cache():
    """Pool exhaustion with the cache ON: parked prefix blocks are
    reclaimed under pressure, sequences preempt/requeue, and every
    request still produces its solo tokens."""
    cfg, params = tiny_lm(11, **CFG_KW)
    shared = list(np.random.RandomState(5).randint(0, 64, 9))
    prompts = [shared + [t] for t in (1, 2, 3)]
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=64, warm=False)
        solo = [srv.generate("g", p, max_new_tokens=20).result(300)
                ["tokens"] for p in prompts]
    metrics.zero_all()
    with InferenceServer() as srv:
        # 7 usable blocks for 3 growing sequences + parked prefix
        srv.load_generative("g", cfg, params, kv_blocks=8, warm=False,
                            prefix_cache=True)
        futs = [srv.generate("g", p, max_new_tokens=20)
                for p in prompts]
        res = [f.result(300) for f in futs]
    preempts = metrics.counter("serve_kv_preemptions_total").value
    assert preempts > 0, "pool was never exhausted — test is vacuous"
    for i, (s, r) in enumerate(zip(solo, res)):
        assert s == r["tokens"], \
            "request %d diverged under preemption+cache" % i


def test_eviction_drops_unreachable_subtree():
    """Reclaiming a parked parent chunk drops its trie node AND every
    parked descendant (they are unreachable: a lookup can never walk
    through a missing parent)."""
    eng = _engine(kv_blocks=8)        # 7 usable
    try:
        idx = eng.prefix_cache
        prompt = list(range(24))      # 3 full chunks: parent chain
        a = _Req(prompt)
        assert idx.acquire(a)
        idx.insert(a)
        eng.pool.free(a.blocks)       # all parked (cacheable)
        assert idx.nodes == 3
        parked = eng.pool.cached_blocks
        assert parked >= 3
        # pressure: demand everything allocatable — the parent chunk
        # is reclaimed and the chain under it goes with it
        got = eng.pool.alloc(eng.pool.free_blocks)
        assert got is not None
        assert idx.nodes == 0
        assert eng.pool.cached_blocks == 0
        eng.pool.free(got)
    finally:
        eng.close()
