"""Self-lint gate: every book-example program this repo trains in its
own tests (tests/test_book_models*.py builders) plus a transpiled
distributed program must verify with ZERO error-severity diagnostics —
the verifier's false-positive budget on known-good programs is zero.
Also exercises tools/lint_program.py end to end on a saved inference
model."""
import os
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.analysis import Severity

import test_book_models as book1
import test_book_models2 as book2


BUILDERS = [
    ("fit_a_line", book1.build_fit_a_line),
    ("recognize_digits_mlp", book1.build_recognize_digits_mlp),
    ("recognize_digits_conv", book1.build_recognize_digits_conv),
    ("word2vec_embeddings", book1.build_word2vec_embeddings),
    ("understand_sentiment_conv", book2.build_understand_sentiment_conv),
    ("understand_sentiment_dyn_rnn",
     book2.build_understand_sentiment_dyn_rnn),
    ("resnet_cifar", book2.build_resnet_cifar),
]


def _errors(diags):
    return [d for d in diags if d.severity == Severity.ERROR]


@pytest.mark.parametrize("name,builder", BUILDERS,
                         ids=[n for n, _ in BUILDERS])
def test_book_program_lints_clean(prog_scope, name, builder):
    main, startup, scope = prog_scope
    builder()
    for label, prog in (("main", main), ("startup", startup)):
        errs = _errors(analysis.verify_program(prog))
        assert errs == [], "%s %s program: %s" % (
            name, label, "\n".join(d.format() for d in errs))


def test_layout_transformed_resnet_lints_clean(prog_scope):
    """ISSUE 5 cross-feature gate: the NHWC layout-transformed +
    stage-fused ResNet training program (rewritten VarDescs, pinned HWIO
    filters, fused_conv2d_bn_act fwd+grad ops, boundary transposes)
    must pass the PR 3 program verifier with ZERO errors — the shape
    checker re-derives every rewritten shape through the lowerings."""
    from paddle_tpu.models import resnet

    main, startup, scope = prog_scope
    resnet.get_model(data_set="cifar10", depth=8, data_format="NHWC",
                     fused_stages=True)
    assert any(op.type == "fused_conv2d_bn_act"
               for op in main.desc.blocks[0].ops)
    for label, prog in (("main", main), ("startup", startup)):
        errs = _errors(analysis.verify_program(prog))
        assert errs == [], "layout-transformed %s program: %s" % (
            label, "\n".join(d.format() for d in errs))


def test_sp_ring_transformer_lints_clean(prog_scope):
    """ISSUE 15 cross-feature gate: the sequence-parallel ring-attention
    training program — ring_attention ops carrying the REAL saved-LSE
    output and ring_attention_grad ops consuming it — must pass the
    verifier with ZERO errors, with the lifetime checker in the
    pipeline."""
    from paddle_tpu.models.transformer import get_model

    main, startup, scope = prog_scope
    get_model(vocab_size=64, seq_len=16, d_model=32, n_head=4,
              n_layers=2, d_ff=64, tp=True, sp=True)
    ring_ops = [op for op in main.desc.blocks[0].ops
                if op.type == "ring_attention"]
    assert ring_ops and all(op.outputs.get("LSE") for op in ring_ops)
    assert any(op.type == "ring_attention_grad"
               for op in main.desc.blocks[0].ops)
    for label, prog in (("main", main), ("startup", startup)):
        errs = _errors(analysis.verify_program(prog))
        assert errs == [], "sp ring %s program: %s" % (
            label, "\n".join(d.format() for d in errs))


def test_fused_transformer_lints_clean(prog_scope):
    """ISSUE 7 cross-feature gate: the fused-transformer-transformed
    training program (fused_qkv_matmul / fused_matmul_bias_act /
    fused_add_ln fwd+grad ops, dropped chain intermediates) must pass
    the PR 3 program verifier with ZERO errors — the shape checker
    re-derives every fused op's outputs through its registered
    infer_shape."""
    from paddle_tpu.models import transformer

    main, startup, scope = prog_scope
    transformer.get_model(vocab_size=101, seq_len=16, d_model=32,
                          n_head=4, n_layers=2, d_ff=64,
                          fuse_transformer=True)
    ops = [op.type for op in main.desc.blocks[0].ops]
    for t in ("fused_qkv_matmul", "fused_matmul_bias_act",
              "fused_add_ln", "fused_add_ln_grad"):
        assert t in ops
    for label, prog in (("main", main), ("startup", startup)):
        errs = _errors(analysis.verify_program(prog))
        assert errs == [], "fused-transformer %s program: %s" % (
            label, "\n".join(d.format() for d in errs))


def test_transpiled_dist_programs_lint_clean(prog_scope):
    main, startup, scope = prog_scope
    book1.build_fit_a_line()
    eps = "127.0.0.1:6281,127.0.0.1:6282"
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers=eps, trainers=2)
    assert _errors(analysis.verify_program(main)) == []
    assert _errors(analysis.verify_program(startup)) == []
    pserver_descs = {}
    for ep in t.pserver_endpoints:
        ps = t.get_pserver_program(ep)
        assert _errors(analysis.verify_program(ps)) == []
        su = t.get_startup_program(ep, ps)
        assert _errors(analysis.verify_program(su)) == []
        pserver_descs[ep] = ps.desc
    assert analysis.verify_transpiled_pair(main.desc, pserver_descs) == []


def test_transpiled_ctr_pair_lints_clean(prog_scope):
    """ISSUE 14 gate extension: the CTR family — a distributed_lookup
    (is_distributed embedding) model transpiled for 2 pservers, the
    PR 10 data plane's program shape — must lint zero-error on every
    program (trainer main/startup, both pservers + startups) AND pass
    the cross-program pairing check, with the new lifetime checker in
    the pipeline."""
    import dist_train_helpers as helpers

    main, startup, scope = prog_scope
    helpers.build_model("emb_dist")
    eps = "127.0.0.1:6291,127.0.0.1:6292"
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers=eps, trainers=2, min_block_size=64)
    assert any(op.type == "distributed_lookup"
               for op in main.desc.blocks[0].ops)
    assert _errors(analysis.verify_program(main)) == []
    assert _errors(analysis.verify_program(startup)) == []
    pserver_descs = {}
    for ep in t.pserver_endpoints:
        ps = t.get_pserver_program(ep)
        assert _errors(analysis.verify_program(ps)) == []
        su = t.get_startup_program(ep, ps)
        assert _errors(analysis.verify_program(su)) == []
        pserver_descs[ep] = ps.desc
    assert analysis.verify_transpiled_pair(main.desc, pserver_descs) == []


def test_serving_predict_program_lints_clean(prog_scope, exe, tmp_path):
    """ISSUE 14 gate extension: the serving family — the PR 9 predict
    program exactly as load_inference_model hands it to the engine
    (pruned test-mode graph, feed/fetch ops appended) — must lint
    zero-error, notably against the new lifetime fetch-of-donated
    rule."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[64], dtype="float32")
    h = fluid.layers.fc(input=x, size=32, act="tanh")
    out = fluid.layers.fc(input=h, size=16, act="softmax")
    exe.run(startup)
    model_dir = str(tmp_path / "serve_model")
    fluid.io.save_inference_model(model_dir, ["x"], [out], exe,
                                  main_program=main)
    prog, feeds, fetches = fluid.io.load_inference_model(model_dir, exe)
    errs = _errors(analysis.verify_program(prog))
    assert errs == [], "\n".join(d.format() for d in errs)


def test_generative_decode_program_lints_clean(prog_scope):
    """ISSUE 14 gate extension: the generative decode shape — a
    seq-len-1 LM step (embedding gather -> blocks -> lm_head matmul,
    the token-granularity program family PR 11 serves) — must lint
    zero-error, shape checker included."""
    main, startup, scope = prog_scope
    ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(ids, size=[64, 32])
    h = fluid.layers.reduce_mean(emb, dim=1)       # [N, 32]
    h = fluid.layers.fc(input=h, size=32, act="relu")
    logits = fluid.layers.fc(input=h, size=64)     # lm_head [N, V]
    fluid.layers.softmax(logits)
    for label, prog in (("main", main), ("startup", startup)):
        errs = _errors(analysis.verify_program(prog))
        assert errs == [], "generative decode %s program: %s" % (
            label, "\n".join(d.format() for d in errs))


def test_autosharded_transformer_lints_clean(prog_scope):
    """ISSUE 20 gate: a transformer training program carrying the FULL
    auto-sharding annotation set (weights, activations, @GRAD mirrors,
    optimizer-state mirrors, desc.mesh_axes stash) must pass the
    verifier — including the new 'sharding' checker that validates spec
    arity, duplicate axes, and static-dim divisibility against the
    stashed mesh — with ZERO errors."""
    from paddle_tpu.models.transformer import get_model
    from paddle_tpu.parallel import spmd

    main, startup, scope = prog_scope
    get_model(vocab_size=64, seq_len=16, d_model=32, n_head=4,
              n_layers=2, d_ff=64)
    placement = spmd.auto_shard(main, 8, cost_model=spmd.CostModel(),
                                batch_size=8)
    spmd.apply_placement(main, placement)
    assert main.desc.var_shardings, "auto-sharding annotated nothing"
    assert getattr(main.desc, "mesh_axes", None)
    for label, prog in (("main", main), ("startup", startup)):
        errs = _errors(analysis.verify_program(prog))
        assert errs == [], "auto-sharded %s program: %s" % (
            label, "\n".join(d.format() for d in errs))


def test_autosharded_resnet_lints_clean(prog_scope):
    """ISSUE 20 gate: same contract on the convolutional family — the
    propagation rules must not fabricate illegal specs through conv /
    batch-norm / pooling chains."""
    from paddle_tpu.models import resnet
    from paddle_tpu.parallel import spmd

    main, startup, scope = prog_scope
    resnet.get_model(data_set="cifar10", depth=8)
    placement = spmd.auto_shard(main, 4, cost_model=spmd.CostModel(),
                                batch_size=8)
    spmd.apply_placement(main, placement)
    assert main.desc.var_shardings
    for label, prog in (("main", main), ("startup", startup)):
        errs = _errors(analysis.verify_program(prog))
        assert errs == [], "auto-sharded resnet %s program: %s" % (
            label, "\n".join(d.format() for d in errs))


def test_resharded_pair_lints_clean(prog_scope):
    """ISSUE 20 elastic gate: re-lowering the SAME program for a
    shrunk mesh (8 -> 4) must produce a layout that (a) lints
    zero-error and (b) passes the dist-pairing reshard checker against
    the old layout."""
    from paddle_tpu.models.transformer import get_model
    from paddle_tpu.parallel import spmd

    main, startup, scope = prog_scope
    get_model(vocab_size=64, seq_len=16, d_model=32, n_head=4,
              n_layers=2, d_ff=64)
    cm = spmd.CostModel()
    spmd.apply_placement(main, spmd.auto_shard(
        main, 8, cost_model=cm, batch_size=8))
    old_shardings = dict(main.desc.var_shardings)
    old_axes = dict(main.desc.mesh_axes)
    spmd.apply_placement(main, spmd.auto_shard(
        main, 4, cost_model=cm, batch_size=8))
    diags = spmd.check_reshard_pair(
        main.desc, old_shardings, old_axes,
        dict(main.desc.var_shardings), dict(main.desc.mesh_axes))
    errs = [d for d in diags if d.severity == Severity.ERROR]
    assert errs == [], "\n".join(d.format() for d in errs)
    errs = _errors(analysis.verify_program(main))
    assert errs == [], "resharded program: %s" % (
        "\n".join(d.format() for d in errs))


def test_lint_cli_on_saved_inference_model(prog_scope, exe, tmp_path):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y_predict = fluid.layers.fc(input=x, size=1, act=None)
    exe.run(startup)
    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["x"], [y_predict], exe,
                                  main_program=main)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools"))
    try:
        import lint_program
    finally:
        sys.path.pop(0)
    assert lint_program.main([model_dir, "--quiet"]) == 0
    # a seeded defect must flip the exit code
    from paddle_tpu.core.desc import ProgramDesc
    with open(os.path.join(model_dir, "__model__"), "rb") as f:
        prog = ProgramDesc.parse_from_string(f.read())
    for op in prog.blocks[0].ops:
        op.rename_input("x", "ghost")  # orphan the fc's real input
    bad = str(tmp_path / "bad_model")
    with open(bad, "wb") as f:
        f.write(prog.serialize_to_string())
    assert lint_program.main([bad, "--quiet"]) == 1
    # unparseable input is reported, not crashed on
    junk = str(tmp_path / "junk")
    with open(junk, "wb") as f:
        f.write(b"\x00not a proto")
    assert lint_program.main([junk, "--quiet"]) == 2
