"""Test env: 8 virtual CPU devices so multi-device SPMD paths are exercised
without TPU hardware (SURVEY §4.3: reference simulates clusters with fake
multi-place lists; here a forced host-device mesh plays that role)."""
import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope


@pytest.fixture
def prog_scope():
    """Fresh main/startup programs + scope + name generator per test."""
    main = fluid.Program()
    startup = fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                yield main, startup, scope


@pytest.fixture
def exe():
    return fluid.Executor(fluid.CPUPlace())
