"""Test env: 8 virtual CPU devices so multi-device SPMD paths are exercised
without TPU hardware (SURVEY §4.3: reference simulates clusters with fake
multi-place lists; here a forced host-device mesh plays that role).

The platform is FORCED, not defaulted: a rig that exports
JAX_PLATFORMS=axon (or any accelerator plugin) would otherwise win the
setdefault and the "CPU-only" suite hangs inside backend init before its
first test.  Same discipline as __graft_entry__._force_cpu_platform:
set the env, then pin the already-imported config (and drop any live
backend) so the selection takes effect regardless of import order."""
import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

try:
    import jax.extend.backend
    # no-op when nothing is initialized; otherwise drops a live
    # accelerator client created before this conftest ran
    jax.extend.backend.clear_backends()
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope


@pytest.fixture
def prog_scope():
    """Fresh main/startup programs + scope + name generator per test."""
    main = fluid.Program()
    startup = fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                yield main, startup, scope


@pytest.fixture
def exe():
    return fluid.Executor(fluid.CPUPlace())
