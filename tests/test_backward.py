"""append_backward graph tests (cf. reference unittests asserting on op
lists — the cheap deterministic layer, SURVEY §4.3)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import OpRole


def test_grad_op_emission(prog_scope):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.fc(x, size=3)
    loss = fluid.layers.mean(y)
    p_g = fluid.append_backward(loss)
    types = [op.type for op in main.global_block().ops]
    assert "mean_grad" in types
    assert "mul_grad" in types
    assert "elementwise_add_grad" in types
    # backward ops marked with the Backward role
    roles = [op.desc.role for op in main.global_block().ops
             if op.type.endswith("_grad")]
    assert all(r & OpRole.Backward for r in roles)
    # one (param, grad) pair per trainable param (w + b)
    assert len(p_g) == 2
    for p, g in p_g:
        assert g.name == p.name + "@GRAD"
        assert tuple(g.shape) == tuple(p.shape)


def test_duplicate_grad_summed(prog_scope, exe):
    """x used twice -> contributions summed (reference
    _addup_repetitive_outputs_)."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[3], dtype="float32")
    x.stop_gradient = False
    y = fluid.layers.elementwise_mul(x, x)  # dy/dx = 2x
    loss = fluid.layers.reduce_sum(y)
    grads = fluid.calc_gradient(loss, [x])
    types = [op.type for op in main.global_block().ops]
    assert "sum" in types, "duplicate grad contributions must be summed"
    xs = np.array([[1.0, 2.0, 3.0]], np.float32)
    g, = exe.run(main, feed={"x": xs}, fetch_list=[grads[0]])
    np.testing.assert_allclose(g, 2 * xs, rtol=1e-6)


def test_stop_gradient_pruning(prog_scope):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")  # stop_grad
    w_frozen = fluid.layers.create_parameter([4, 2], "float32",
                                             name="frozen")
    w_frozen.trainable = False
    w_frozen.stop_gradient = True
    h = fluid.layers.mul(x, w_frozen)
    loss = fluid.layers.mean(h)
    p_g = fluid.append_backward(loss)
    assert p_g == []
    grad_names = [n for n in main.global_block().vars if "@GRAD" in n]
    assert "frozen@GRAD" not in grad_names


def test_unused_branch_skipped(prog_scope):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    a = fluid.layers.fc(x, size=2)
    b = fluid.layers.fc(x, size=2)  # not on loss path
    loss = fluid.layers.mean(a)
    fluid.append_backward(loss)
    ops = [op.type for op in main.global_block().ops]
    # exactly one mul_grad (for a's fc), not two
    assert ops.count("mul_grad") == 1


def test_grad_matches_jax_grad(prog_scope, exe):
    """Whole-graph analytic grads vs jax.grad over an equivalent jnp
    function: the strongest oracle available."""
    import jax
    import jax.numpy as jnp
    main, startup, scope = prog_scope
    np.random.seed(4)
    xs = np.random.randn(5, 4).astype(np.float32)
    w0 = np.random.randn(4, 8).astype(np.float32)
    b0 = np.zeros(8, np.float32)
    w1 = np.random.randn(8, 1).astype(np.float32)

    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(x, size=8, act="tanh",
                        param_attr=fluid.ParamAttr(name="w0"),
                        bias_attr=fluid.ParamAttr(name="b0"))
    y = fluid.layers.fc(h, size=1, act=None,
                        param_attr=fluid.ParamAttr(name="w1"),
                        bias_attr=False)
    loss = fluid.layers.mean(y)
    p_g = fluid.append_backward(loss)
    exe.run(startup)
    scope.set("w0", w0)
    scope.set("b0", b0)
    scope.set("w1", w1)
    grad_map = {p.name: g.name for p, g in p_g}
    got = exe.run(main, feed={"x": xs},
                  fetch_list=[grad_map["w0"], grad_map["b0"],
                              grad_map["w1"]])

    # the environment's default matmul precision is reduced (TPU-style);
    # force full f32 in the oracle to match the framework's mul lowering,
    # which sets preferred_element_type=f32
    @jax.default_matmul_precision("highest")
    def f(params):
        h_ = jnp.tanh(xs @ params["w0"] + params["b0"])
        return jnp.mean(h_ @ params["w1"])

    want = jax.grad(f)({"w0": w0, "b0": b0, "w1": w1})
    np.testing.assert_allclose(got[0], want["w0"], atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(got[1], want["b0"], atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(got[2], want["w1"], atol=1e-4, rtol=1e-4)
