"""Batched/overlapped pserver data plane (ISSUE 4).

Pins the contracts the full-duplex round rests on, in-process (real
VariableServer + RPCClient over real sockets, no spawned trainers):

- bit-exact dense + sparse round parity between the batched fastwire
  scatter/gather and the unbatched per-variable wire;
- idempotence of dropped/duplicated BATCHED frames under the PR 1
  (round, sender, seq) dedup — replays never skew the sync mean;
- per-shard completion events: a streamed gather returns a shard the
  moment ITS apply commits, not when the whole round does;
- the multi-send-op retry regression: a faulted later send op must
  resend ITS tensors, not just whatever the round cache already holds;
- a tier-1 smoke of ``tools/pserver_bench.py --quick`` so data-plane
  regressions surface in the normal suite.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core.scope import Scope
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.distributed.resilience import FLAGS, install_faults
from paddle_tpu.distributed.rpc import RPCClient, VariableServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    install_faults("")
    prev_batch, prev_overlap = (FLAGS.pserver_wire_batch,
                                FLAGS.pserver_overlap)
    yield
    install_faults("")
    FLAGS.pserver_wire_batch = prev_batch
    FLAGS.pserver_overlap = prev_overlap
    RPCClient.reset()


def _sgd_server(scope, grads_to_params, fanin, **kw):
    """VariableServer whose block b applies SGD(lr=1) for grad b
    (dense subtract, or scatter-subtract for SelectedRows grads)."""
    items = list(grads_to_params.items())

    def apply_block(bid):
        g, p = items[bid]
        gv = scope.find_var(g)
        pv = np.array(np.asarray(scope.find_var(p)), copy=True)
        if isinstance(gv, SelectedRows):
            np.subtract.at(pv, np.asarray(gv.rows),
                           np.asarray(gv.values))
        else:
            pv -= np.asarray(gv)
        scope.set(p, pv)

    srv = VariableServer(
        scope, {g: i for i, (g, _) in enumerate(items)}, apply_block,
        fanin=fanin, grad_params={g: (p,) for g, p in items}, **kw)
    port = srv.start("127.0.0.1:0")
    return srv, "127.0.0.1:%d" % port


def _run_rounds(batched, rounds=3):
    """One trainer pair x N sync rounds against a 2-shard server;
    returns the fetched param values per round."""
    FLAGS.pserver_wire_batch = bool(batched)
    scope = Scope()
    scope.set("p1", np.zeros((8, 4), np.float32))
    scope.set("p2", np.zeros((50, 8), np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1", "g2": "p2"}, fanin=2)
    RPCClient.reset()
    a, b = RPCClient.instance(), RPCClient()
    fetched = []
    rng = np.random.RandomState(7)
    try:
        for r in range(rounds):
            for cli, k in ((a, 1.0), (b, 3.0)):
                rows = np.arange(0, 10, 2, dtype=np.int64) + r
                vals = (rng.rand(5, 8) * 0 + k).astype(np.float32)
                cli.send_vars([
                    (ep, "g1", np.full((8, 4), k * (r + 1), np.float32)),
                    (ep, "g2", SelectedRows(rows, vals, 50)),
                ])
            ts = [threading.Thread(target=c.send_barrier, args=([ep],))
                  for c in (a, b)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            got = a.get_vars([(ep, "p1"), (ep, "p2")])
            fetched.append([np.array(np.asarray(x), copy=True)
                            for x in got])
    finally:
        a.send_complete([ep])
        b.send_complete([ep])
        srv.wait()
    return fetched


def test_batched_matches_unbatched_bit_exact():
    """Dense + SelectedRows rounds over the batched scatter/gather must
    be BIT-EXACT against the per-variable wire."""
    batched = _run_rounds(batched=True)
    legacy = _run_rounds(batched=False)
    assert len(batched) == len(legacy)
    for rb, rl in zip(batched, legacy):
        for vb, vl in zip(rb, rl):
            np.testing.assert_array_equal(vb, vl)


def test_batched_replay_and_duplicates_are_idempotent():
    """Duplicated batched frames (client replay after a reconnect) must
    dedup by (round, sender, seq): the sync mean counts each trainer
    once no matter how many times its batch lands."""
    FLAGS.pserver_wire_batch = True
    scope = Scope()
    scope.set("p1", np.zeros(4, np.float32))
    scope.set("p2", np.zeros(3, np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1", "g2": "p2"}, fanin=2)
    RPCClient.reset()
    a, b = RPCClient.instance(), RPCClient()
    try:
        a.send_vars([(ep, "g1", np.full(4, 2.0, np.float32)),
                     (ep, "g2", np.full(3, 4.0, np.float32))])
        # duplicate batch + full round replay — what a retry does
        a.send_vars([(ep, "g1", np.full(4, 2.0, np.float32)),
                     (ep, "g2", np.full(3, 4.0, np.float32))])
        a._replay_round(ep)
        b.send_vars([(ep, "g1", np.full(4, 4.0, np.float32)),
                     (ep, "g2", np.full(3, 8.0, np.float32))])
        ts = [threading.Thread(target=c.send_barrier, args=([ep],))
              for c in (a, b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        p1, p2 = a.get_vars([(ep, "p1"), (ep, "p2")])
        np.testing.assert_allclose(np.asarray(p1), np.full(4, -3.0))
        np.testing.assert_allclose(np.asarray(p2), np.full(3, -6.0))
    finally:
        a.send_complete([ep])
        b.send_complete([ep])
        srv.wait()


def test_faulted_send_op_resends_its_own_tensors():
    """Regression: with an earlier send op's grads already in the round
    cache, a FAULTED later send op must resend ITS tensors — filtering
    the retry by the cache silently dropped them (the shard then missed
    the round entirely and parity broke under fault injection)."""
    FLAGS.pserver_wire_batch = True
    scope = Scope()
    scope.set("p1", np.zeros(4, np.float32))
    scope.set("p2", np.zeros(3, np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1", "g2": "p2"}, fanin=1)
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        # send op 1 lands; send op 2 is dropped once and must retry
        cli.send_vars([(ep, "g1", np.full(4, 2.0, np.float32))])
        install_faults("send_grad:drop:1.0:1")
        cli.send_vars([(ep, "g2", np.full(3, 5.0, np.float32))])
        cli.send_barrier([ep])
        p1, p2 = cli.get_vars([(ep, "p1"), (ep, "p2")])
        np.testing.assert_allclose(np.asarray(p1), np.full(4, -2.0))
        np.testing.assert_allclose(np.asarray(p2), np.full(3, -5.0))
    finally:
        install_faults("")
        cli.send_complete([ep])
        srv.wait()


def test_streamed_gather_returns_shard_before_round_completes():
    """Per-shard completion events: with shard g2's optimize block
    artificially slow, a batched get of (p1, p2) receives p1 while g2
    is still applying — the gather no longer gates on the whole round."""
    FLAGS.pserver_wire_batch = True
    scope = Scope()
    scope.set("p1", np.zeros(4, np.float32))
    scope.set("p2", np.zeros(3, np.float32))
    slow = threading.Event()
    t_first = {}

    def apply_block(bid):
        if bid == 0:        # g1 -> p1: instant
            scope.set("p1", np.asarray(scope.find_var("p1"))
                      - np.asarray(scope.find_var("g1")))
        else:               # g2 -> p2: slow
            slow.set()
            time.sleep(0.8)
            scope.set("p2", np.asarray(scope.find_var("p2"))
                      - np.asarray(scope.find_var("g2")))

    srv = VariableServer(scope, {"g1": 0, "g2": 1}, apply_block,
                         fanin=1, grad_params={"g1": ("p1",),
                                               "g2": ("p2",)})
    ep = "127.0.0.1:%d" % srv.start("127.0.0.1:0")
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        cli.send_vars([(ep, "g1", np.ones(4, np.float32)),
                       (ep, "g2", np.ones(3, np.float32))])
        bt = threading.Thread(target=cli.send_barrier, args=([ep],))
        bt.start()

        def sink(name):
            def _s(arr):
                t_first[name] = time.time()
                return np.array(np.asarray(arr), copy=True)
            return _s

        t0 = time.time()
        p1, p2 = cli.get_vars([(ep, "p1"), (ep, "p2")], round_=1,
                              sinks=[sink("p1"), sink("p2")])
        bt.join()
        np.testing.assert_allclose(p1, np.full(4, -1.0))
        np.testing.assert_allclose(p2, np.full(3, -1.0))
        # p1 streamed while g2's apply was still sleeping
        assert t_first["p1"] - t0 < 0.6
        assert t_first["p2"] - t_first["p1"] > 0.3
    finally:
        cli.send_complete([ep])
        srv.wait()


def test_overlapped_barriers_join_surfaces_errors():
    """launch_barriers + join_barriers: the ack (and any failure) of an
    overlapped barrier lands at the join, and the round counter has
    already advanced so the in-flight gets name the right round."""
    FLAGS.pserver_wire_batch = True
    scope = Scope()
    scope.set("p1", np.zeros(2, np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1"}, fanin=1)
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        cli.send_vars([(ep, "g1", np.ones(2, np.float32))])
        step_before = cli.step
        cli.launch_barriers([ep])
        assert cli.step == step_before + 1
        got, = cli.get_vars([(ep, "p1")])
        cli.join_barriers()
        np.testing.assert_allclose(np.asarray(got), np.full(2, -1.0))
        # the ack implied durability: the server finished the round
        assert srv._durable_round == cli.step
    finally:
        cli.send_complete([ep])
        srv.wait()


@pytest.mark.parametrize("compress", ["", "int8", "topk"])
def test_quick_bench_smoke(compress):
    """tools/pserver_bench.py --quick completes in seconds and reports
    sane round-throughput machinery fields (tier-1 guard: a data-plane
    regression that stalls or crashes the round shows up here).
    Parametrized over the FLAGS_dist_compress codecs so a codec that
    wedges or corrupts the real 2x2 spawned round fails tier-1, not
    just the in-process tests (ISSUE 10 satellite).  The sweep/CTR
    scenarios stay out of tier-1 (measured by the full bench run)."""
    out = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                       "psb_quick_%d_%s.json" % (os.getpid(),
                                                 compress or "raw"))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FLAGS_dist_compress=compress)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pserver_bench.py"),
         "--quick", "--json", out, "--no-floor", "--no-ctr",
         "--no-sweep"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(out) as f:
        rec = json.loads(f.read())
    os.unlink(out)
    assert rec["metric"] == "pserver_bench"
    assert rec["quick"] is True
    assert rec["dense_rounds_per_sec"] > 0
    assert rec["sparse_steps_per_sec"] > 0
    assert rec["dense_round_ms"] > 0
    assert rec["pservers"] == 2 and rec["trainers"] == 2
    # the stdout artifact is the same single JSON line
    line = [l for l in proc.stdout.splitlines() if l.strip()][-1]
    assert json.loads(line)["metric"] == "pserver_bench"
