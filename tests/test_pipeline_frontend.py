"""Pipeline parallelism from the fluid front-end (fluid/pipeline.py):
a Program split at cut vars trains on a multi-device pipeline and
matches single-device training exactly."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _build(scope):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = layers.data(name="x", shape=[8], dtype="float32")
                y = layers.data(name="y", shape=[1], dtype="float32")
                h1 = layers.fc(x, size=16, act="tanh",
                               param_attr=fluid.ParamAttr(name="w1"),
                               bias_attr=fluid.ParamAttr(name="b1"))
                h2 = layers.fc(h1, size=16, act="tanh",
                               param_attr=fluid.ParamAttr(name="w2"),
                               bias_attr=fluid.ParamAttr(name="b2"))
                pred = layers.fc(h2, size=1,
                                 param_attr=fluid.ParamAttr(name="w3"),
                                 bias_attr=fluid.ParamAttr(name="b3"))
                loss = layers.mean(
                    layers.square_error_cost(pred, y))
    return main, startup, h1, h2, loss


def test_pipeline_matches_single_device():
    import jax

    devices = jax.devices("cpu")
    if len(devices) < 3:
        pytest.skip("needs 3 host devices")

    rng = np.random.RandomState(0)
    xv = rng.randn(8, 8).astype(np.float32)
    yv = (xv.sum(1, keepdims=True) * 0.3).astype(np.float32)
    lr, steps, n_mb = 0.05, 5, 4

    # pipeline programs + their own init
    scope_b = fluid.Scope()
    main_b, startup_b, h1, h2, loss_b = _build(scope_b)
    with fluid.scope_guard(scope_b):
        exe_b = fluid.Executor(fluid.CPUPlace())
        exe_b.run(startup_b)

    # exact baseline: replay single-device training from scope_b's init
    scope_c = fluid.Scope()
    main_c, startup_c, _, _, loss_c = _build(scope_c)
    with fluid.scope_guard(scope_c):
        with fluid.program_guard(main_c, startup_c):
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss_c)
        exe_c = fluid.Executor(fluid.CPUPlace())
        exe_c.run(startup_c)
        for n in ("w1", "b1", "w2", "b2", "w3", "b3"):
            scope_c.set(n, np.asarray(scope_b.find_var(n)))
        base = []
        for _ in range(steps):
            l, = exe_c.run(main_c, feed={"x": xv, "y": yv},
                           fetch_list=[loss_c])
            base.append(float(np.ravel(l)[0]))
        base_w1 = np.asarray(scope_c.find_var("w1"))

    from paddle_tpu.fluid.pipeline import PipelineProgram

    pp = PipelineProgram(main_b, loss_b, cut_vars=[h1, h2],
                         devices=devices[:3], scope=scope_b,
                         feed_names=["x", "y"])
    pipe = [pp.train_step({"x": xv, "y": yv}, n_microbatches=n_mb,
                          lr=lr) for _ in range(steps)]
    # microbatch-mean grads == full-batch grads for a mean loss, so the
    # trajectories must match to float tolerance
    np.testing.assert_allclose(pipe, base, rtol=1e-4, atol=1e-6)
    pp.sync_to_scope(scope_b)
    np.testing.assert_allclose(np.asarray(scope_b.find_var("w1")),
                               base_w1, rtol=1e-4, atol=1e-6)


def _build_with_adam(scope, lr):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = layers.data(name="x", shape=[8], dtype="float32")
                y = layers.data(name="y", shape=[1], dtype="float32")
                h1 = layers.fc(x, size=16, act="tanh",
                               param_attr=fluid.ParamAttr(name="w1"),
                               bias_attr=fluid.ParamAttr(name="b1"))
                pred = layers.fc(h1, size=1,
                                 param_attr=fluid.ParamAttr(name="w2"),
                                 bias_attr=fluid.ParamAttr(name="b2"))
                loss = layers.mean(layers.square_error_cost(pred, y))
                fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, h1, loss


def test_pipeline_runs_program_adam():
    """A pipelined program that minimized with Adam trains with ADAM —
    trajectory matches single-device Adam; passing lr= raises."""
    import jax

    devices = jax.devices("cpu")
    if len(devices) < 2:
        pytest.skip("needs 2 host devices")

    rng = np.random.RandomState(1)
    xv = rng.randn(8, 8).astype(np.float32)
    yv = (xv.sum(1, keepdims=True) * 0.3).astype(np.float32)
    lr, steps, n_mb = 0.01, 4, 4

    scope_p = fluid.Scope()
    main_p, startup_p, h1, loss_p = _build_with_adam(scope_p, lr)
    with fluid.scope_guard(scope_p):
        fluid.Executor(fluid.CPUPlace()).run(startup_p)

    # single-device Adam baseline from the same init
    scope_c = fluid.Scope()
    main_c, startup_c, _, loss_c = _build_with_adam(scope_c, lr)
    with fluid.scope_guard(scope_c):
        exe_c = fluid.Executor(fluid.CPUPlace())
        exe_c.run(startup_c)
        for n in ("w1", "b1", "w2", "b2"):
            scope_c.set(n, np.asarray(scope_p.find_var(n)))
        base = []
        for _ in range(steps):
            l, = exe_c.run(main_c, feed={"x": xv, "y": yv},
                           fetch_list=[loss_c])
            base.append(float(np.ravel(l)[0]))

    from paddle_tpu.fluid.pipeline import PipelineProgram

    pp = PipelineProgram(main_p, loss_p, cut_vars=[h1],
                         devices=devices[:2], scope=scope_p,
                         feed_names=["x", "y"])
    with pytest.raises(ValueError, match="drop lr"):
        pp.train_step({"x": xv, "y": yv}, n_microbatches=n_mb, lr=lr)
    pipe = [pp.train_step({"x": xv, "y": yv}, n_microbatches=n_mb)
            for _ in range(steps)]
    np.testing.assert_allclose(pipe, base, rtol=1e-4, atol=1e-6)


def test_pipeline_without_optimizer_requires_lr():
    import jax

    devices = jax.devices("cpu")
    if len(devices) < 3:
        pytest.skip("needs 3 host devices")
    scope = fluid.Scope()
    main, startup, h1, h2, loss = _build(scope)
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
    from paddle_tpu.fluid.pipeline import PipelineProgram
    pp = PipelineProgram(main, loss, cut_vars=[h1, h2],
                         devices=devices[:3], scope=scope,
                         feed_names=["x", "y"])
    x = np.zeros((4, 8), np.float32)
    y = np.zeros((4, 1), np.float32)
    with pytest.raises(ValueError, match="pass lr"):
        pp.train_step({"x": x, "y": y}, n_microbatches=2)


def test_pipeline_external_write_wins_and_restages():
    """External scope writes between pipeline steps (a checkpoint load,
    a user scope.set) win over the stage-resident copies: the flush
    must not clobber them and the next step trains FROM them."""
    import jax

    devices = jax.devices("cpu")
    if len(devices) < 3:
        pytest.skip("needs 3 host devices")

    rng = np.random.RandomState(4)
    xv = rng.randn(8, 8).astype(np.float32)
    yv = (xv.sum(1, keepdims=True) * 0.3).astype(np.float32)
    lr, n_mb = 0.05, 4

    scope_b = fluid.Scope()
    main_b, startup_b, h1, h2, loss_b = _build(scope_b)
    with fluid.scope_guard(scope_b):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup_b)

    # baseline replays the same schedule single-device from the same
    # init, including the mid-training external reset of w1
    scope_c = fluid.Scope()
    main_c, startup_c, _, _, loss_c = _build(scope_c)
    with fluid.scope_guard(scope_c):
        with fluid.program_guard(main_c, startup_c):
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss_c)
        exe_c = fluid.Executor(fluid.CPUPlace())
        exe_c.run(startup_c)
        for n in ("w1", "b1", "w2", "b2", "w3", "b3"):
            scope_c.set(n, np.asarray(scope_b.find_var(n)))

    from paddle_tpu.fluid.pipeline import PipelineProgram

    pp = PipelineProgram(main_b, loss_b, cut_vars=[h1, h2],
                         devices=devices[:3], scope=scope_b,
                         feed_names=["x", "y"])
    marker = np.zeros((8, 16), np.float32)

    pp.train_step({"x": xv, "y": yv}, n_microbatches=n_mb, lr=lr)
    scope_b.set("w1", marker.copy())  # external write while dirty
    # a flushing read must NOT clobber the external value
    np.testing.assert_array_equal(
        fluid.fetch_var("w1", scope=scope_b), marker)
    pp.train_step({"x": xv, "y": yv}, n_microbatches=n_mb, lr=lr)
    pp.sync_scope()

    with fluid.scope_guard(scope_c):
        exe_c.run(main_c, feed={"x": xv, "y": yv},
                  fetch_list=[loss_c])
        scope_c.set("w1", marker.copy())
        exe_c.run(main_c, feed={"x": xv, "y": yv},
                  fetch_list=[loss_c])
    np.testing.assert_allclose(
        np.asarray(scope_b.find_var("w1")),
        np.asarray(scope_c.find_var("w1")), rtol=1e-4, atol=1e-6)
