#!/usr/bin/env python
"""Run the fault-injection resilience suite standalone, across a matrix
of FLAGS_fault_spec presets.

The tier-1 run excludes the process-killing tests (pytest -m 'not
slow'); this driver is the standalone harness: for each preset it runs
``tests/test_resilience.py`` (slow tests included) with the preset
exported as FLAGS_fault_spec, and prints a pass/fail table.

Usage:
    python tools/fault_matrix.py                  # full preset matrix
    python tools/fault_matrix.py drop_heavy mixed # chosen presets
    python tools/fault_matrix.py --list
    python tools/fault_matrix.py --spec "send_grad:drop:0.5:10"  # ad hoc

Notes:
  - The spawned trainer/pserver workers of the slow tests set their own
    fault env per-test; the preset here ADDITIONALLY applies to every
    in-process injection point, so heavier presets genuinely stress the
    retry/replay machinery harder.
  - FLAGS_fault_seed is pinned per run for reproducibility; pass
    --seed 0 for OS entropy.
"""
import argparse
import glob
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRESETS = {
    "none": "",
    "drop_light": "send_grad:drop:0.05,get_param:drop:0.05",
    "drop_heavy": ("send_grad:drop:0.3:20,get_param:drop:0.3:20,"
                   "send_barrier:drop:0.3:10"),
    "delay": "get_param:delay:0.1,send_grad:delay:0.05",
    "master_flaky": "master_rpc:drop:0.2:20",
    "mixed": ("send_grad:drop:0.15:15,get_param:delay:0.05:10,"
              "get_param:drop:0.15:15,send_barrier:drop:0.25:6,"
              "master_rpc:drop:0.1:10"),
    # numerics observatory (ISSUE 8): poison ONE wire gradient with NaN
    # at sync round 2 and require the pserver-side attribution artifact
    # — run_numerics_preset() runs tests/test_numerics.py and FAILs
    # unless a numerics_*.json names the poisoned round's cid
    "numerics": "send_grad:corrupt:%d:1" % 2,
    # compressed wire (ISSUE 10): the drop/replay/SIGKILL-restart
    # resilience suite over int8-quantized frames.  The e2e parity
    # tests switch their reference to a FAULT-FREE compressed
    # distributed run (test_resilience._baseline), so a pass means
    # exact-loss-parity holds: retries/replays ship the cached
    # compressed frames bit-identically and PR 1's idempotence
    # guarantees survive the codec.
    "compressed": ("send_grad:drop:0.2:12,get_param:drop:0.2:12,"
                   "send_barrier:drop:0.3:6"),
    # scale observatory (ISSUE 12): drive the pending-state collapse
    # mode in tools/scale_bench.py (one straggler under a k=3 window)
    # and FAIL unless the ledger tripwire left a flight artifact whose
    # embedded ledger SERIES shows the growth — run_scale_preset()
    "scale": "",
    # Watchtower (ISSUE 13): inject a LATENCY fault into the serving
    # dispatch path during a short serve+train loop with the tsdb
    # sampler + SLO evaluator armed, and FAIL unless a burn-rate
    # alert fires and its flight dump names the violated SLO and
    # embeds the offending series — run_slo_preset()
    "slo": "serve_dispatch:delay:0.02",
    # Disaggregated serving fleet (ISSUE 16): SIGKILL one decode AND
    # one prefill worker in the middle of the fleet bench's kill drill
    # and FAIL unless ZERO requests were lost (tokens bit-identical to
    # the unkilled baseline) and EACH eviction left a flight artifact
    # naming the dead worker — run_serve_fleet_preset()
    "serve_fleet": "",
    # Sanitizer suite (ISSUE 14): plant a use-after-donate (direct
    # host read of a donated param mid-prepared-loop) and a lock-order
    # inversion under FLAGS_sanitizer=all, and FAIL unless both leave
    # NAMED artifacts — a sanitizer:buffer:* flight dump carrying the
    # planted var name, and a lockgraph_<pid>.json whose cycle lists
    # both planted locks — run_sanitizer_preset()
    "sanitizer": "",
    # Weaver schedule explorer (ISSUE 18): re-introduce the historical
    # KV double-free behind --plant and FAIL (rc 3) unless the explorer
    # finds it, minimizes it, and leaves a weaver_*.json whose failure
    # names the racing sites — run_weaver_preset()
    "weaver": "",
    # Prefix-cache refcounts (ISSUE 19): same drill over the
    # kv_refcount scenario with the pre-refcount lost-decref release
    # re-introduced (--plant dropped_decref) — the shared prefix block
    # leaks unless the terminal decref runs exactly once
    "kv_refcount": "",
    # Elastic mesh reshard (ISSUE 20): SIGKILL the trainer in the
    # window between the quiesce checkpoint and the 8->4 re-lowering,
    # relaunch in recovery mode, and FAIL (rc 3) unless the recovered
    # shrunken mesh reproduces the expected loss trajectory (PARITY)
    # and the reshard left a flight artifact — run_reshard_preset()
    "reshard": "",
}

# the names the sanitizer preset's plants use (tests/test_sanitizer.py
# fault_plant tests) and this runner greps the artifacts for
SANITIZER_PLANT_VAR = "sanitizer_plant_w"
SANITIZER_PLANT_LOCKS = ("plant.A", "plant.B")

# extra environment a preset exports into the pytest run (and, by
# inheritance, into every spawned trainer/pserver worker)
PRESET_ENV = {
    "compressed": {"FLAGS_dist_compress": "int8"},
}

NUMERICS_ROUND = 2


def run_numerics_preset(pytest_args):
    """The 'numerics' preset is an end-to-end attribution check, not a
    resilience sweep: tests/test_numerics.py sends a NaN-poisoned
    gradient at round NUMERICS_ROUND through the real wire, and this
    runner FAILs (rc 3) unless the run leaves a numerics_*.json flight
    artifact whose cid is exactly that round — the breadcrumb that
    makes a poisoned round attributable to the trainer that sent it."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FLAGS_check_numerics"] = "guard"
    dump_dir = tempfile.mkdtemp(prefix="fault_flight_numerics_")
    env["FLAGS_telemetry_dump_dir"] = dump_dir
    cmd = [sys.executable, "-m", "pytest", "tests/test_numerics.py",
           "-q", "-p", "no:cacheprovider", "-o", "addopts="] + pytest_args
    t0 = time.time()
    proc = subprocess.run(cmd, cwd=REPO, env=env)
    rc = proc.returncode
    want_cid = "round:%d" % NUMERICS_ROUND
    arts = glob.glob(os.path.join(dump_dir, "numerics_*.json"))
    matched = 0
    for path in arts:
        try:
            import json
            with open(path) as f:
                if json.load(f).get("cid") == want_cid:
                    matched += 1
        except Exception:
            pass
    if rc == 0 and matched == 0:
        print("preset 'numerics': no numerics_*.json naming cid %r "
              "under %s — the poisoned round was not attributed"
              % (want_cid, dump_dir), file=sys.stderr)
        rc = 3
    if rc == 0:
        shutil.rmtree(dump_dir, ignore_errors=True)
    else:
        print("preset 'numerics' FAILED (rc=%d); artifacts kept at %s"
              % (rc, dump_dir), file=sys.stderr)
    return rc, time.time() - t0, dump_dir, matched


def run_scale_preset():
    """The 'scale' preset is a collapse-forensics check, not a fault
    sweep: tools/scale_bench.py --collapse pending drives real pending-
    state growth on a real pserver (straggler + staleness window), and
    this runner FAILs (rc 3) unless a flight_*.json lands whose
    'ledger' section carries a non-empty time series including the
    pending-grad resource — the breadcrumb that makes a 256-trainer
    collapse diagnosable after the fact."""
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out_json = os.path.join(tempfile.mkdtemp(prefix="fault_scale_"),
                            "scale.json")
    cmd = [sys.executable, "tools/scale_bench.py", "--quick",
           "--no-sweep", "--collapse", "pending", "--json", out_json]
    t0 = time.time()
    proc = subprocess.run(cmd, cwd=REPO, env=env,
                          stdout=subprocess.DEVNULL)
    rc = proc.returncode
    dump_dir, matched = "", 0
    try:
        with open(out_json) as f:
            col = json.load(f).get("collapse", {})
        dump_dir = col.get("dump_dir", "")
        arts = glob.glob(os.path.join(dump_dir, "flight_*.json"))
        for path in arts:
            with open(path) as f:
                led = json.load(f).get("ledger") or {}
            series = led.get("series") or []
            if any("pserver_pending_grad_bytes" in s.get("values", {})
                   for s in series):
                matched += 1
    except Exception:
        pass
    if rc == 0 and matched == 0:
        print("preset 'scale': no flight_*.json with ledger rows "
              "naming pserver_pending_grad_bytes under %r — the "
              "collapse was not attributed" % dump_dir,
              file=sys.stderr)
        rc = 3
    if rc == 0:
        shutil.rmtree(dump_dir, ignore_errors=True)
        shutil.rmtree(os.path.dirname(out_json), ignore_errors=True)
    else:
        print("preset 'scale' FAILED (rc=%d); artifacts kept at %s"
              % (rc, dump_dir or out_json), file=sys.stderr)
    return rc, time.time() - t0, dump_dir, matched


def run_slo_preset(spec, pytest_args):
    """The 'slo' preset is a burn-rate drill, not a resilience sweep:
    tests/test_slo.py's fault drill runs a short serve+train loop with
    the Watchtower sampler + SLO evaluator on while the injected
    ``serve_dispatch`` delay blows the request-latency SLO, and this
    runner FAILs (rc 3) unless a flight_*.json with an ``slo:*``
    reason lands whose embedded alert names the violated SLO and
    carries a non-empty offending series — the breadcrumb that makes
    a burned error budget diagnosable after the fact."""
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FLAGS_fault_spec"] = spec
    dump_dir = tempfile.mkdtemp(prefix="fault_flight_slo_")
    env["FLAGS_telemetry_dump_dir"] = dump_dir
    cmd = [sys.executable, "-m", "pytest", "tests/test_slo.py",
           "-q", "-k", "fault_drill", "-p", "no:cacheprovider",
           "-o", "addopts="] + pytest_args
    t0 = time.time()
    proc = subprocess.run(cmd, cwd=REPO, env=env)
    rc = proc.returncode
    matched = 0
    for path in glob.glob(os.path.join(dump_dir, "flight_*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        if not str(rec.get("reason", "")).startswith("slo:"):
            continue
        alert = (rec.get("slo") or {}).get("alert") or {}
        if alert.get("slo") and alert.get("series"):
            matched += 1
    if rc == 0 and matched == 0:
        print("preset 'slo': no flight_*.json with an slo:* reason "
              "naming the violated SLO + offending series under %s — "
              "the burned budget was not attributed" % dump_dir,
              file=sys.stderr)
        rc = 3
    if rc == 0:
        shutil.rmtree(dump_dir, ignore_errors=True)
    else:
        print("preset 'slo' FAILED (rc=%d); artifacts kept at %s"
              % (rc, dump_dir), file=sys.stderr)
    return rc, time.time() - t0, dump_dir, matched


def run_serve_fleet_preset():
    """The 'serve_fleet' preset is a kill-survival drill, not a fault
    sweep: tools/serve_fleet_bench.py spawns real prefill/decode worker
    processes, replays one Poisson schedule twice, and SIGKILLs one
    decode AND one prefill worker mid-run (--kill both).  This runner
    FAILs (rc 3) unless the killed run lost ZERO requests, its greedy
    tokens are bit-identical to the unkilled baseline, and EVERY
    eviction left a flight_*.json naming the dead worker — a fleet
    that survives a kill but can't say who died is a FAIL."""
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    dump_dir = tempfile.mkdtemp(prefix="fault_fleet_dump_")
    env["FLAGS_telemetry_dump_dir"] = dump_dir
    out_json = os.path.join(dump_dir, "fleet.json")
    cmd = [sys.executable, "tools/serve_fleet_bench.py",
           "--kill", "both", "--replicas", "3", "--prefill-workers",
           "2", "--seconds", "8", "--floor-seconds", "3",
           "--burst-seconds", "6", "--kill-seconds", "10",
           "--out", out_json]
    t0 = time.time()
    proc = subprocess.run(cmd, cwd=REPO, env=env,
                          stdout=subprocess.DEVNULL)
    rc, matched = 0, 0
    try:
        with open(out_json) as f:
            out = json.load(f)
        kill = out.get("kill") or {}
        victims = kill.get("victims") or []
        artifacts = kill.get("artifacts") or {}
        matched = sum(1 for v in victims if artifacts.get(v))
        survived = (kill.get("lost") == 0 and kill.get("parity")
                    and len(victims) >= 2
                    and matched == len(victims))
        if not survived:
            print("preset 'serve_fleet': kill drill not survived "
                  "cleanly (lost=%r parity=%r victims=%r artifacts "
                  "naming the dead: %d/%d) under %s"
                  % (kill.get("lost"), kill.get("parity"), victims,
                     matched, len(victims), dump_dir), file=sys.stderr)
            rc = 3
    except Exception as e:
        print("preset 'serve_fleet': bench produced no parseable "
              "result (%s; bench rc=%d); artifacts kept at %s"
              % (e, proc.returncode, dump_dir), file=sys.stderr)
        rc = 3
    if rc == 0:
        shutil.rmtree(dump_dir, ignore_errors=True)
    else:
        print("preset 'serve_fleet' FAILED (rc=%d); artifacts kept "
              "at %s" % (rc, dump_dir), file=sys.stderr)
    return rc, time.time() - t0, dump_dir, matched


def run_reshard_preset():
    """The 'reshard' preset is the elastic-mesh kill drill (ISSUE 20):
    tools/autoshard_bench.py --shrink-drill trains the auto-sharded
    transformer at p=8, quiesces, writes the PR 1 shard checkpoint plus
    the expected post-quiesce loss trajectory, raises a marker file,
    and pauses — this runner SIGKILLs it inside that window (mid-shrink,
    after state is durable, before the 4-device re-lowering exists).
    The relaunch with --recover must rebuild the program, restore the
    checkpoint through spmd.reshard(checkpoint_dir=...), and reproduce
    the expected trajectory on the SHRUNKEN mesh.  rc 3 unless the
    recovery's drill_result.json reports parity_ok AND the reshard left
    a flight artifact under the dump dir — a shrink that loses the loss
    trajectory, or one that leaves no breadcrumb of the mesh change,
    is a FAIL."""
    import json
    import signal  # noqa: F401  (SIGKILL via Popen.kill)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["AUTOSHARD_DRILL_PAUSE_S"] = "30"
    dump_dir = tempfile.mkdtemp(prefix="fault_reshard_dump_")
    env["FLAGS_telemetry_dump_dir"] = dump_dir
    marker = os.path.join(dump_dir, "pre_shrink_ready")
    t0 = time.time()
    proc = subprocess.Popen(
        [sys.executable, "tools/autoshard_bench.py", "--shrink-drill",
         "--dump-dir", dump_dir],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL)
    killed = False
    deadline = time.time() + 300
    while time.time() < deadline and proc.poll() is None:
        if os.path.exists(marker):
            time.sleep(0.5)  # let the marker write land
            proc.kill()      # SIGKILL: no atexit, no flush, no mercy
            proc.wait()
            killed = True
            break
        time.sleep(0.5)
    if not killed:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        print("preset 'reshard': drill never reached the kill window "
              "(marker %s missing; rc=%r); artifacts kept at %s"
              % (marker, proc.returncode, dump_dir), file=sys.stderr)
        return 3, time.time() - t0, dump_dir, 0

    rec_proc = subprocess.run(
        [sys.executable, "tools/autoshard_bench.py", "--shrink-drill",
         "--recover", "--dump-dir", dump_dir],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL, timeout=300)
    rc, n_dumps = 0, 0
    try:
        with open(os.path.join(dump_dir, "drill_result.json")) as f:
            rec = json.load(f)
        flight = rec.get("flight_artifact")
        n_dumps = 1 if flight and os.path.exists(flight) else 0
        survived = (rec_proc.returncode == 0 and rec.get("recovered")
                    and rec.get("parity_ok") and n_dumps == 1)
        if not survived:
            print("preset 'reshard': kill drill not survived cleanly "
                  "(recover rc=%d recovered=%r parity_ok=%r "
                  "parity_max_rel=%r flight=%r) under %s"
                  % (rec_proc.returncode, rec.get("recovered"),
                     rec.get("parity_ok"), rec.get("parity_max_rel"),
                     flight, dump_dir), file=sys.stderr)
            rc = 3
    except Exception as e:
        print("preset 'reshard': recovery produced no parseable "
              "drill_result.json (%s; recover rc=%d); artifacts kept "
              "at %s" % (e, rec_proc.returncode, dump_dir),
              file=sys.stderr)
        rc = 3
    if rc == 0:
        shutil.rmtree(dump_dir, ignore_errors=True)
    else:
        print("preset 'reshard' FAILED (rc=%d); artifacts kept at %s"
              % (rc, dump_dir), file=sys.stderr)
    return rc, time.time() - t0, dump_dir, n_dumps


def run_sanitizer_preset(pytest_args):
    """The 'sanitizer' preset is a named-artifact drill, not a fault
    sweep: tests/test_sanitizer.py's fault plants run with
    FLAGS_sanitizer=all — one direct host read of a donated parameter
    mid-prepared-loop, one deliberate A->B / B->A lock-order inversion
    — and this runner FAILs (rc 3) unless BOTH left artifacts naming
    the culprits: a flight_*.json with a sanitizer:buffer:* reason
    carrying the planted var name, and a lockgraph_*.json whose cycle
    (or inversion) lists both planted locks.  A run where the plants
    trip but the breadcrumbs are anonymous is a FAIL — naming the
    culprit is the whole point of the suite."""
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FLAGS_sanitizer"] = "all"
    dump_dir = tempfile.mkdtemp(prefix="fault_flight_sanitizer_")
    env["FLAGS_telemetry_dump_dir"] = dump_dir
    cmd = [sys.executable, "-m", "pytest", "tests/test_sanitizer.py",
           "-q", "-k", "fault_plant", "-p", "no:cacheprovider",
           "-o", "addopts="] + pytest_args
    t0 = time.time()
    proc = subprocess.run(cmd, cwd=REPO, env=env)
    rc = proc.returncode
    buf_named = lock_named = 0
    for path in glob.glob(os.path.join(dump_dir, "flight_*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        reason = str(rec.get("reason", ""))
        blocked = rec.get("blocked") or {}
        if reason.startswith("sanitizer:buffer:") \
                and blocked.get("var") == SANITIZER_PLANT_VAR:
            buf_named += 1
    a, b = SANITIZER_PLANT_LOCKS
    for path in glob.glob(os.path.join(dump_dir, "lockgraph_*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        rings = [c.get("locks", []) for c in rec.get("cycles", [])]
        rings += [c.get("locks", []) for c in rec.get("inversions", [])]
        if any(a in locks and b in locks for locks in rings):
            lock_named += 1
    if rc == 0 and (buf_named == 0 or lock_named == 0):
        print("preset 'sanitizer': missing named artifact(s) under %s "
              "(buffer dumps naming %r: %d; lockgraphs cycling %r<->%r:"
              " %d) — the planted bugs were not attributed"
              % (dump_dir, SANITIZER_PLANT_VAR, buf_named, a, b,
                 lock_named), file=sys.stderr)
        rc = 3
    if rc == 0:
        shutil.rmtree(dump_dir, ignore_errors=True)
    else:
        print("preset 'sanitizer' FAILED (rc=%d); artifacts kept at %s"
              % (rc, dump_dir), file=sys.stderr)
    return rc, time.time() - t0, dump_dir, buf_named + lock_named


def run_preset(name, spec, seed, pytest_args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FLAGS_fault_spec"] = spec
    env.update(PRESET_ENV.get(name, {}))
    if seed:
        env["FLAGS_fault_seed"] = str(seed)
    # flight recorder (ISSUE 6): with a dump dir set, the first fault
    # firing per injection point and every WatchdogTimeout leave a
    # flight_*.json artifact here — asserted below for every preset
    # that actually injects faults
    dump_dir = tempfile.mkdtemp(prefix="fault_flight_%s_" % name)
    env["FLAGS_telemetry"] = "1"
    env["FLAGS_telemetry_dump_dir"] = dump_dir
    # generous budgets: heavy drop presets legitimately retry a lot
    env.setdefault("FLAGS_rpc_deadline", "300")
    env.setdefault("FLAGS_rpc_call_timeout", "15")
    # -o addopts= clears the repo default `-m "not slow"`: this runner
    # exists precisely to exercise the slow process-killing tests
    cmd = [sys.executable, "-m", "pytest", "tests/test_resilience.py",
           "-q", "-p", "no:cacheprovider", "-o", "addopts="] + pytest_args
    t0 = time.time()
    proc = subprocess.run(cmd, cwd=REPO, env=env)
    n_dumps = len(glob.glob(os.path.join(dump_dir, "flight_*.json")))
    return proc.returncode, time.time() - t0, dump_dir, n_dumps


def run_weaver_preset(scenario="kv_pool", plant="double_free"):
    """The 'weaver' preset is a find-the-planted-race drill: run the
    schedule explorer (tools/weaver.py) over ``scenario`` with a
    historical race re-introduced (``--plant``) and FAIL (rc 3) unless
    the run (a) finds a failing schedule (explorer rc 1), and (b)
    leaves a minimized weaver_<scenario>_*.json artifact whose failure
    block NAMES the racing sites.  An anonymous failure — found but
    unattributed — is a FAIL, same contract as the sanitizer preset.
    The 'kv_refcount' preset routes here with plant=dropped_decref:
    the pre-refcount shared-prefix release whose lost decref leaks the
    block."""
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    dump_dir = tempfile.mkdtemp(prefix="fault_weaver_")
    cmd = [sys.executable, os.path.join(REPO, "tools", "weaver.py"),
           "--scenario", scenario, "--plant", plant,
           "--preemption-bound", "2", "--out-dir", dump_dir]
    t0 = time.time()
    proc = subprocess.run(cmd, cwd=REPO, env=env)
    rc = proc.returncode
    named = 0
    for path in glob.glob(
            os.path.join(dump_dir, "weaver_%s_*.json" % scenario)):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        failure = rec.get("failure") or {}
        sites = failure.get("sites") or []
        if failure.get("type") and sites \
                and rec.get("trace") is not None:
            named += 1
    if rc == 1 and named > 0:
        rc = 0                      # found + minimized + attributed
    elif rc in (0, 1):
        print("weaver preset: planted %s/%s not attributed "
              "under %s (explorer rc=%d, named artifacts=%d)"
              % (scenario, plant, dump_dir, rc, named), file=sys.stderr)
        rc = 3
    if rc == 0:
        shutil.rmtree(dump_dir, ignore_errors=True)
    else:
        print("weaver preset %s/%s FAILED (rc=%d); artifacts kept at "
              "%s" % (scenario, plant, rc, dump_dir), file=sys.stderr)
    return rc, time.time() - t0, dump_dir, named


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="fault-injection suite matrix runner")
    ap.add_argument("presets", nargs="*",
                    help="preset names (default: the whole matrix)")
    ap.add_argument("--preset", action="append", default=[],
                    dest="preset_flags", metavar="NAME",
                    help="preset name (flag form; may repeat — merged "
                         "with the positional list)")
    ap.add_argument("--list", action="store_true",
                    help="list presets and exit")
    ap.add_argument("--spec", default=None,
                    help="ad-hoc FLAGS_fault_spec instead of presets")
    ap.add_argument("--seed", type=int, default=1234,
                    help="FLAGS_fault_seed (0 = OS entropy)")
    ap.add_argument("--fast-only", action="store_true",
                    help="skip the process-spawning slow tests")
    args, extra = ap.parse_known_args(argv)

    if args.list:
        for name, spec in PRESETS.items():
            print("%-14s %s" % (name, spec or "<no faults>"))
        return 0

    pytest_args = list(extra)
    if args.fast_only:
        pytest_args += ["-m", "not slow"]

    if args.spec is not None:
        matrix = [("adhoc", args.spec)]
    else:
        names = (list(args.presets) + list(args.preset_flags)) \
            or list(PRESETS)
        unknown = [n for n in names if n not in PRESETS]
        if unknown:
            ap.error("unknown preset(s) %s; --list shows the matrix"
                     % unknown)
        matrix = [(n, PRESETS[n]) for n in names]

    rows = []
    for name, spec in matrix:
        print("=== preset %r: FLAGS_fault_spec=%r" % (name, spec))
        if name == "numerics":
            rc, secs, dump_dir, n_dumps = run_numerics_preset(
                pytest_args)
            rows.append((name, rc, secs, n_dumps))
            continue
        if name == "scale":
            rc, secs, dump_dir, n_dumps = run_scale_preset()
            rows.append((name, rc, secs, n_dumps))
            continue
        if name == "slo":
            rc, secs, dump_dir, n_dumps = run_slo_preset(spec,
                                                         pytest_args)
            rows.append((name, rc, secs, n_dumps))
            continue
        if name == "sanitizer":
            rc, secs, dump_dir, n_dumps = run_sanitizer_preset(
                pytest_args)
            rows.append((name, rc, secs, n_dumps))
            continue
        if name == "serve_fleet":
            rc, secs, dump_dir, n_dumps = run_serve_fleet_preset()
            rows.append((name, rc, secs, n_dumps))
            continue
        if name == "weaver":
            rc, secs, dump_dir, n_dumps = run_weaver_preset()
            rows.append((name, rc, secs, n_dumps))
            continue
        if name == "kv_refcount":
            rc, secs, dump_dir, n_dumps = run_weaver_preset(
                scenario="kv_refcount", plant="dropped_decref")
            rows.append((name, rc, secs, n_dumps))
            continue
        if name == "reshard":
            rc, secs, dump_dir, n_dumps = run_reshard_preset()
            rows.append((name, rc, secs, n_dumps))
            continue
        rc, secs, dump_dir, n_dumps = run_preset(name, spec, args.seed,
                                                 pytest_args)
        # a preset that injects faults must leave a flight-recorder
        # artifact (observability/flight.note_fault dumps on the first
        # firing per point) — a silent injected-fault run means the
        # breadcrumb path is broken
        missing = bool(spec) and n_dumps == 0 and rc == 0
        if missing:
            print("preset %r: no flight_*.json under %s despite "
                  "injected faults" % (name, dump_dir), file=sys.stderr)
            rc = 3
        if rc == 0:
            # passing presets clean their flight dir (repeated CI runs
            # would otherwise accumulate temp dirs without bound);
            # failures keep theirs as the diagnostic breadcrumb
            shutil.rmtree(dump_dir, ignore_errors=True)
        else:
            print("preset %r FAILED (rc=%d); flight dumps kept at %s"
                  % (name, rc, dump_dir), file=sys.stderr)
        rows.append((name, rc, secs, n_dumps))

    print("\n%-14s %-6s %-8s %s" % ("preset", "result", "seconds",
                                    "flight_dumps"))
    worst = 0
    for name, rc, secs, n_dumps in rows:
        print("%-14s %-6s %-8.1f %d" % (
            name, "PASS" if rc == 0 else "FAIL", secs, n_dumps))
        worst = max(worst, rc)
    return worst


if __name__ == "__main__":
    sys.exit(main())
