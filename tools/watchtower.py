#!/usr/bin/env python
"""Watchtower operational report (ISSUE 13 tentpole d): one shot,
one page — is the system healthy, and is it getting slower?

Renders, from a Watchtower tsdb root (``--tsdb``) and/or a live
flight/trace dump dir (``--dump-dir``):

- the **SLO table**: every spec from ``--slo`` (a JSON/TOML file or
  inline objectives, default FLAGS_slo_spec) evaluated read-only
  against each per-process store — objective, last value, fast/slow
  burn rates, error budget remaining, FIRING markers.  Evaluation here
  never writes flight dumps (it is somebody else's store);
- **active alerts**: slo:* flight dumps found in the dump dir (the
  durable evidence a burn fired) plus any currently-firing windows;
- **hot series sparklines**: the busiest series per store (ranked by
  recent variation), downsampled to a unicode sparkline row with
  min/last/max — the collapse curve at a glance;
- **last bench deltas**: PERF_TRAJECTORY.json's recorded floor vs the
  latest run per metric (tools/perf_sentinel.py builds it), regressions
  marked.

Usage:
    python tools/watchtower.py --tsdb /ckpt/tsdb --slo slo.json
    python tools/watchtower.py --dump-dir /tmp/dumps
    python tools/watchtower.py --tsdb D --trajectory PERF_TRAJECTORY.json --json
"""
import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width=32):
    """Unicode sparkline over ``values`` (downsampled to ``width``)."""
    vals = [float(v) for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:
        # mean-pool into width buckets
        out = []
        n = len(vals)
        for b in range(width):
            lo, hi = b * n // width, max(b * n // width + 1,
                                         (b + 1) * n // width)
            chunk = vals[lo:hi]
            out.append(sum(chunk) / len(chunk))
        vals = out
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK[0] * len(vals)
    return "".join(SPARK[min(len(SPARK) - 1,
                             int((v - lo) / (hi - lo)
                                 * (len(SPARK) - 1)))]
                   for v in vals)


def _slo_section(stores, specs):
    """Read-only SLO evaluation of every store: one row per
    (store, slo).  Windows anchor at the STORE's newest sample, not
    wall-clock now — a collapse read back hours later must still
    show its burn, not an empty (and therefore 'healthy') window."""
    from paddle_tpu.observability import slo as _slo

    rows = []
    for label, store in sorted(stores.items()):
        as_of = store.last_time()
        ev = _slo.Evaluator(store, specs, dump_alerts=False)
        for r in ev.evaluate(now=as_of):
            fast = r["windows"]["fast"]
            slow = r["windows"]["slow"]
            rows.append({
                "store": label, "slo": r["name"],
                "as_of": as_of,
                "objective": r["objective"],
                "last_value": r["last_value"],
                "burn_fast": fast["burn"],
                "burn_slow": slow["burn"],
                "samples_fast": fast["samples"],
                "budget_remaining": r["budget_remaining"],
                "firing": [w for w in ("fast", "slow")
                           if r["windows"][w]["firing"]],
            })
    return rows


def _alerts_section(dump_dir):
    """slo:* flight dumps under the dump dir — the durable alert
    evidence (reason, slo, window, burn, when)."""
    alerts = []
    for path in sorted(glob.glob(os.path.join(dump_dir,
                                              "flight_*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        reason = rec.get("reason", "")
        if not reason.startswith("slo:"):
            continue
        detail = (rec.get("slo") or {}).get("alert") or {}
        blocked = rec.get("blocked") or {}
        alerts.append({
            "file": os.path.basename(path),
            "reason": reason,
            "slo": detail.get("slo") or blocked.get("slo"),
            "window": detail.get("window") or blocked.get("window"),
            "burn": detail.get("burn") or blocked.get("burn"),
            "objective": detail.get("objective")
            or blocked.get("objective"),
            "wall_time": rec.get("wall_time"),
            "series_samples": len(detail.get("series") or []),
        })
    return alerts


def _hot_series(stores, top=8, buckets=32):
    """Busiest series per store: ranked by coefficient of variation
    over the retained window (a flat counter of any size is boring; a
    swinging gauge is the story), sparkline rendered from the
    downsample."""
    rows = []
    for label, store in sorted(stores.items()):
        scored = []
        for name in store.names():
            t, v = store.scan(name)
            if len(v) < 2:
                continue
            mean = float(abs(v).mean())
            spread = float(v.max() - v.min())
            if spread <= 0:
                continue
            scored.append((spread / (mean + 1e-12), name))
        scored.sort(reverse=True)
        for _score, name in scored[:top]:
            ds = store.downsample(name, buckets=buckets)
            means = [d["mean"] for d in ds]
            rows.append({
                "store": label, "series": name,
                "spark": sparkline(means),
                "min": round(min(d["min"] for d in ds), 4),
                "last": round(means[-1], 4) if means else 0.0,
                "max": round(max(d["max"] for d in ds), 4),
                "n": int(sum(d["count"] for d in ds)),
            })
    return rows


def _bench_section(trajectory_path):
    try:
        with open(trajectory_path) as f:
            traj = json.load(f)
    except Exception:
        return []
    rows = []
    for name, ent in sorted((traj.get("metrics") or {}).items()):
        floor, latest = ent.get("floor"), ent.get("latest")
        if floor in (None, 0):
            continue
        if ent.get("higher_is_better", True):
            delta = (latest - floor) / abs(floor)
        else:
            delta = (floor - latest) / abs(floor)
        rows.append({"metric": name, "floor": floor, "latest": latest,
                     "delta_frac": round(delta, 4),
                     "runs": len(ent.get("runs", [])),
                     "regressed": delta < -0.15})
    return rows


def build_report(tsdb_root=None, dump_dir=None, slo_spec=None,
                 trajectory=None):
    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.observability import slo as _slo
    from paddle_tpu.observability import tsdb as _tsdb

    report = {"kind": "watchtower_report"}
    stores = _tsdb.open_stores(tsdb_root) if tsdb_root else {}
    report["stores"] = sorted(stores)
    specs = _slo.load_specs(slo_spec if slo_spec is not None
                            else FLAGS.slo_spec)
    if stores and specs:
        report["slo"] = _slo_section(stores, specs)
    if dump_dir:
        report["alerts"] = _alerts_section(dump_dir)
    if stores:
        report["hot_series"] = _hot_series(stores)
    traj_path = trajectory or os.path.join(REPO,
                                           "PERF_TRAJECTORY.json")
    bench = _bench_section(traj_path)
    if bench:
        report["bench"] = bench
        report["trajectory"] = traj_path
    return report


def render(report):
    out = []
    if report.get("slo") is not None:
        out.append("SLO status (budget remaining over the slow "
                   "window; burn >= threshold fires):")
        out.append("%-18s %-24s %-30s %10s %10s %10s %8s  %s" % (
            "store", "slo", "objective", "last", "burn_fast",
            "burn_slow", "budget", "firing"))
        for r in report["slo"]:
            out.append("%-18s %-24s %-30s %10s %10.2f %10.2f %7.0f%%"
                       "  %s" % (
                           r["store"][:18], r["slo"][:24],
                           r["objective"][:30],
                           ("%.4g" % r["last_value"])
                           if r["last_value"] is not None else "-",
                           r["burn_fast"], r["burn_slow"],
                           100.0 * r["budget_remaining"],
                           ",".join(r["firing"]) or "-"))
        out.append("")
    alerts = report.get("alerts")
    if alerts is not None:
        out.append("alerts (%d slo:* flight dumps):" % len(alerts))
        for a in alerts:
            out.append("  %-28s %-20s window=%-5s burn=%-8s %s  (%d "
                       "series samples) %s" % (
                           a["file"], a.get("slo") or "?",
                           a.get("window") or "?",
                           a.get("burn"), a.get("objective") or "",
                           a.get("series_samples") or 0,
                           a.get("wall_time") or ""))
        if not alerts:
            out.append("  (none)")
        out.append("")
    hot = report.get("hot_series")
    if hot:
        out.append("hot series (by relative swing):")
        for r in hot:
            out.append("  %-16s %-40s %s  min %.4g last %.4g max "
                       "%.4g (%d samples)" % (
                           r["store"][:16], r["series"][:40],
                           r["spark"], r["min"], r["last"], r["max"],
                           r["n"]))
        out.append("")
    bench = report.get("bench")
    if bench:
        out.append("bench trajectory (%s):"
                   % report.get("trajectory", ""))
        out.append("%-40s %7s %12s %12s %9s" % (
            "metric", "runs", "floor", "latest", "delta"))
        for r in bench:
            out.append("%-40s %7d %12.4g %12.4g %+8.1f%%%s" % (
                r["metric"], r["runs"], r["floor"], r["latest"],
                100.0 * r["delta_frac"],
                "  REGRESSED" if r["regressed"] else ""))
    return "\n".join(out) if out else "(nothing to report — pass " \
        "--tsdb and/or --dump-dir)"


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="one-shot Watchtower operational report: SLO "
                    "table, active alerts, hot-series sparklines, "
                    "bench trajectory deltas")
    ap.add_argument("--tsdb", default=None, metavar="DIR",
                    help="Watchtower tsdb root (FLAGS_tsdb_dir of the "
                         "run under inspection)")
    ap.add_argument("--dump-dir", default=None, metavar="DIR",
                    help="flight/trace dump dir to scan for slo:* "
                         "alert artifacts")
    ap.add_argument("--slo", default=None, metavar="SPEC",
                    help="SLO spec file (.json/.toml) or inline "
                         "objectives (default: FLAGS_slo_spec)")
    ap.add_argument("--trajectory", default=None, metavar="PATH",
                    help="PERF_TRAJECTORY.json to diff (default: the "
                         "repo's)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    args = ap.parse_args(argv)

    report = build_report(tsdb_root=args.tsdb, dump_dir=args.dump_dir,
                          slo_spec=args.slo,
                          trajectory=args.trajectory)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
