"""Fused-matmul kernel tuner at the transformer-bench stage shapes.

flash_tune.py's method applied to the ISSUE 7 fused block stages: for
each distinct matmul of the transformer-LM secondary bench (fused QKV,
attention output projection, MLP up/down, lm_head) measure fwd wall
time of kernels/matmul_fused.matmul_epilogue over a grid of
(block_m, block_n, block_k) tiles, plus the fused add+LN row tile —
with the microbench traps handled (distinct pre-staged inputs,
unrolled chain, one final d2h drain).

The per-shape winner lands in the persistent autotune cache
(FLAGS_autotune_cache_dir -> paddle_tpu/tuning); the fused op
lowerings consult it at the next compile, so the sweep self-applies
to every future run of the same shapes.

Usage: FLAGS_autotune_cache_dir=... python tools/matmul_tune.py [steps]
Env: MM_TUNE_BATCH/MM_TUNE_SEQ/MM_TUNE_DMODEL/MM_TUNE_VOCAB override
the secondary-bench dims (16 / 2048 / 1024 / 8192).
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu import tuning  # noqa: E402
from paddle_tpu.kernels import matmul_fused  # noqa: E402

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 8
B = int(os.environ.get("MM_TUNE_BATCH", "16"))
S = int(os.environ.get("MM_TUNE_SEQ", "2048"))
D = int(os.environ.get("MM_TUNE_DMODEL", "1024"))
V = int(os.environ.get("MM_TUNE_VOCAB", "8192"))
M = B * S

# (name, m, k, n, act, residual) — the transformer block's matmuls at
# the secondary-bench shape; qkv is the width-concatenated projection
STAGES = [
    ("qkv", M, D, 3 * D, "", False),
    ("out_proj", M, D, D, "", False),
    ("mlp_up", M, D, 4 * D, "gelu", False),
    ("mlp_down", M, 4 * D, D, "", True),
    ("lm_head", M, D, V, "", False),
]

TILE_GRID = [
    (256, 256, 512),    # built-in defaults
    (512, 256, 512),
    (256, 512, 512),
    (128, 512, 512),
    (512, 512, 256),
    (256, 256, 1024),
    (1024, 256, 512),
    (256, 1024, 512),
    (512, 512, 512),
]

LN_TILES = [128, 256, 512, 1024]


def bench_matmul(m, k, n, act, residual, cfg, dtype=jnp.bfloat16):
    rng = np.random.RandomState(0)
    xs = [jnp.asarray(rng.randn(m, k) * 0.1, dtype)
          for _ in range(STEPS)]
    w = jnp.asarray(rng.randn(k, n) * 0.02, dtype)
    bias = jnp.asarray(rng.randn(n) * 0.1, jnp.float32)
    res = jnp.asarray(rng.randn(m, n) * 0.1, dtype) if residual else None

    def run(ops):
        acc = 0.0
        for x in ops:        # unrolled: STEPS independent launches
            y = matmul_fused.matmul_epilogue(x, w, bias, res, act,
                                             config=cfg)
            acc = acc + y[0, 0].astype(jnp.float32)
        return acc

    jfn = jax.jit(run)
    float(np.asarray(jfn(xs)))            # compile + warm
    t0 = time.time()
    float(np.asarray(jfn(xs)))            # d2h drain = the sync
    return (time.time() - t0) / STEPS


def bench_add_ln(m, d, bm, dtype=jnp.bfloat16):
    rng = np.random.RandomState(0)
    pairs = [(jnp.asarray(rng.randn(m, d), dtype),
              jnp.asarray(rng.randn(m, d), dtype))
             for _ in range(STEPS)]
    scale = jnp.asarray(rng.rand(d) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(d), jnp.float32)

    def run(ops):
        acc = 0.0
        for x, y in ops:
            o, s, mn, vr = matmul_fused.add_ln(
                x, y, scale, bias, config={"block_m": bm})
            acc = acc + o[0, 0].astype(jnp.float32) + s[0, 0].astype(
                jnp.float32)
        return acc

    jfn = jax.jit(run)
    float(np.asarray(jfn(pairs)))
    t0 = time.time()
    float(np.asarray(jfn(pairs)))
    return (time.time() - t0) / STEPS


def tune_stage(name, m, k, n, act, residual, dtype=jnp.bfloat16):
    """Sweep TILE_GRID for one matmul stage and record the winner into
    the autotune cache.  Returns (best_cfg, best_sec)."""
    best_cfg, best_sec = None, None
    print("%s  [%d x %d] @ [%d x %d] act=%r residual=%s"
          % (name, m, k, k, n, act or None, residual))
    for bm, bn, bk in TILE_GRID:
        cfg = {"block_m": bm, "block_n": bn, "block_k": bk}
        _, _, _, usable = matmul_fused.plan_matmul(m, k, n, dtype, cfg)
        try:
            sec = bench_matmul(m, k, n, act, residual, cfg, dtype)
            gflops = 2.0 * m * k * n / sec / 1e9
            print("  (%4d,%4d,%4d)%s %9.2f ms  %8.1f GF/s" %
                  (bm, bn, bk, " " if usable else "*",
                   sec * 1e3, gflops), flush=True)
            if best_sec is None or sec < best_sec:
                best_cfg, best_sec = cfg, sec
        except Exception as exc:  # noqa: BLE001 — tuning survey
            print("  (%4d,%4d,%4d)  FAILED: %s" %
                  (bm, bn, bk, str(exc)[:80]))
    if best_cfg is not None:
        ok = tuning.record("matmul_fused", (m, k, n),
                           jnp.dtype(dtype).name, best_cfg,
                           ms=best_sec * 1e3,
                           source="matmul_tune:%s" % name)
        print("  best %s %s" % (
            best_cfg,
            "-> %s" % tuning.cache_path() if ok else
            "(FLAGS_autotune_cache_dir unset: not persisted)"))
    return best_cfg, best_sec


def main():
    print("transformer matmul sweep M=%d D=%d V=%d, %d unrolled "
          "steps, bf16" % (M, D, V, STEPS))
    for name, m, k, n, act, residual in STAGES:
        tune_stage(name, m, k, n, act, residual)

    best_bm, best_sec = None, None
    print("add_ln  [%d x %d]" % (M, D))
    for bm in LN_TILES:
        try:
            sec = bench_add_ln(M, D, bm)
            print("  block_m=%4d %9.2f ms" % (bm, sec * 1e3),
                  flush=True)
            if best_sec is None or sec < best_sec:
                best_bm, best_sec = bm, sec
        except Exception as exc:  # noqa: BLE001
            print("  block_m=%4d  FAILED: %s" % (bm, str(exc)[:80]))
    if best_bm is not None:
        ok = tuning.record("add_ln", (M, D), "bfloat16",
                           {"block_m": best_bm}, ms=best_sec * 1e3,
                           source="matmul_tune:add_ln")
        print("  best block_m=%d %s" % (
            best_bm, "-> %s" % tuning.cache_path() if ok else
            "(FLAGS_autotune_cache_dir unset: not persisted)"))


if __name__ == "__main__":
    main()
