"""Per-strategy mesh cost report on the 8-device virtual CPU mesh
(VERDICT r5 weak #8: "SPMD replaces the SSA graph" had no quantified
replacement cost).

For each parallelism strategy the 8-device dryrun exercises — dp,
dp x tp, dp x tp x sp, dp x ep (MoE), pp, and the dp x pp composition —
this tool measures:

- **step wall time** over N timed steps (after a warmup/compile step)
  of the same tiny transformer / pipeline programs the dryrun runs, and
- the **collective inventory** of the optimized HLO (XLA dump parsed
  for all-reduce / all-gather / all-to-all / collective-permute
  instructions and their byte sizes) — the concrete replacement for the
  reference's hand-built AllReduce/Broadcast op handles
  (details/multi_devices_graph_builder.cc:232).

Step wall on a virtual CPU mesh is a HOST number (thread-simulated
collectives); the collective inventory is exact compiler output and is
the portable part of the report.  Each strategy runs in a subprocess so
its XLA dump and device-count flags are isolated.

Usage:  python tools/mesh_profile.py [--steps N] [--out MESH_PROFILE.md]
        python tools/mesh_profile.py --child <strategy> <dumpdir>
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_DEV = 8
STRATEGIES = [
    ("dp8", {"dp": 8}),
    ("dp4xtp2", {"dp": 4, "tp": 2}),
    ("dp2xtp2xsp2", {"dp": 2, "tp": 2, "sp": 2}),
    ("dp4xep2", {"dp": 4, "ep": 2}),
    ("pp8", {"pp": 8}),
    ("dp2xpp4", {"dp": 2, "pp": 4}),
]

# r07: the same non-pp strategies lowered through the ISSUE 20
# annotated route — ShardingPass-assigned per-VarDesc specs +
# desc.mesh_axes stash instead of the hand mesh_axes carrier wiring —
# to confirm the annotated lowering reproduces the legacy carriers'
# cost (child names "ann:<strategy>")
ANNOTATED = ["dp8", "dp4xtp2", "dp2xtp2xsp2", "dp4xep2"]

_COLL_RE = re.compile(
    r"\b(all-reduce|all-gather|all-to-all|collective-permute)"
    r"(?:-start|-done)?\b")
_SHAPE_RE = re.compile(r"\b([a-z]+\d+)\[([\d,]*)\]")
_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
                "s32": 4, "u32": 4, "s8": 1, "u8": 1, "pred": 1}


def _timed_transformer(axes, steps, moe=False, annotated=False):
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models.transformer import get_model

    seq = 64
    kwargs = {}
    if moe:
        kwargs = {"moe_experts": 4, "ep": True}
    else:
        kwargs = {"tp": axes.get("tp", 1) > 1, "sp": axes.get("sp", 1) > 1}
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss, (src, label), _ = get_model(
                    vocab_size=64, seq_len=seq, d_model=128, n_head=4,
                    n_layers=2, d_ff=256, **kwargs)
        fluid.Executor(fluid.CPUPlace()).run(startup)
        exec_axes = axes
        if annotated:
            # ISSUE 20 route: same strategy, expressed as per-VarDesc
            # annotations; the executor infers the mesh from the stash
            from paddle_tpu.parallel import spmd
            pl = spmd.placement_for(main, axes, batch_size=max(
                2, 2 * axes.get("dp", 1)))
            spmd.apply_placement(main, pl, scope=scope)
            exec_axes = None
        pe = fluid.ParallelExecutor(
            use_tpu=False, loss_name=loss.name, main_program=main,
            scope=scope, mesh_axes=exec_axes, num_devices=N_DEV)
        dp = axes.get("dp", 1)
        bs = max(2, 2 * dp)
        rng = np.random.RandomState(0)
        xs = rng.randint(0, 64, (bs, seq)).astype(np.int64)
        ys = np.roll(xs, -1, axis=1)[:, :, None].astype(np.int64)
        feed = {src.name: xs, label.name: ys}
        pe.run(feed=feed, fetch_list=[loss])          # warmup/compile
        t0 = time.time()
        out = None
        for _ in range(steps):
            out, = pe.run(feed=feed, fetch_list=[loss])
        np.asarray(out)
        return (time.time() - t0) / steps


def _timed_pipeline(dp, steps):
    import numpy as np

    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel import make_mesh, pipeline_apply

    devices = jax.devices("cpu")[:N_DEV]
    p = N_DEV // dp
    d, m, mb = 16, 4, 2 * dp
    axes = {"pp": p} if dp == 1 else {"dp": dp, "pp": p}
    mesh = make_mesh(axes, devices=devices)
    batch_axis = "dp" if dp > 1 else None
    rng = np.random.RandomState(0)
    with jax.default_device(devices[0]):
        ws = jnp.asarray(rng.randn(p, d, d).astype(np.float32) * 0.3)
        xs = jnp.asarray(rng.randn(m, mb, d).astype(np.float32))
        tgt = jnp.asarray(rng.randn(m, mb, d).astype(np.float32))

        def step_fn(ws):
            out = pipeline_apply(ws, xs, mesh, lambda w, x:
                                 jnp.tanh(x @ w), batch_axis=batch_axis)
            return jnp.mean((out - tgt) ** 2)

        grad = jax.jit(jax.value_and_grad(step_fn))
        loss, g = grad(ws)
        jax.block_until_ready((loss, g))              # warmup/compile
        t0 = time.time()
        for _ in range(steps):
            loss, g = grad(ws)
        jax.block_until_ready((loss, g))
        return (time.time() - t0) / steps


def _collectives_from_dump(dump_dir):
    """Sum collective instruction counts/bytes over the optimized HLO of
    the largest dumped module (the training step; warmup helpers are
    smaller)."""
    paths = []
    for root, _, files in os.walk(dump_dir):
        for f in files:
            if f.endswith("after_optimizations.txt"):
                p = os.path.join(root, f)
                paths.append((os.path.getsize(p), p))
    if not paths:
        return {}

    def scan(path):
        counts = {}
        bbytes = 0
        with open(path) as f:
            for line in f:
                m = _COLL_RE.search(line)
                if not m or "-done" in m.group(0):
                    continue
                kind = m.group(1)
                counts[kind] = counts.get(kind, 0) + 1
                best = 0
                for dt, dims in _SHAPE_RE.findall(line):
                    sz = _DTYPE_BYTES.get(dt)
                    if sz is None:
                        continue
                    n = 1
                    for d in dims.split(","):
                        if d:
                            n *= int(d)
                    best = max(best, n * sz)
                bbytes += best
        counts["bytes"] = bbytes
        counts["module"] = os.path.basename(path)[:60]
        return counts

    # the step module is the one WITH collectives (the startup program's
    # module is usually the largest dump but has none); among candidates
    # take the most collective-heavy, falling back to the largest
    scans = [scan(p) for _, p in sorted(paths, reverse=True)]
    with_colls = [c for c in scans
                  if sum(v for k, v in c.items()
                         if k not in ("bytes", "module")) > 0]
    return max(with_colls, key=lambda c: c["bytes"]) if with_colls \
        else scans[0]


def _run_child(strategy, dump_dir, steps):
    import __graft_entry__ as graft

    graft._force_cpu_platform(N_DEV)
    annotated = strategy.startswith("ann:")
    key = strategy[4:] if annotated else strategy
    name = dict(STRATEGIES)[key]
    if "pp" in name:
        ms = _timed_pipeline(name.get("dp", 1), steps) * 1e3
    else:
        ms = _timed_transformer(name, steps, moe="ep" in name,
                                annotated=annotated) * 1e3
    print(json.dumps({"strategy": strategy, "step_ms": round(ms, 2)}))


def main(argv):
    if len(argv) >= 3 and argv[0] == "--child":
        return _run_child(argv[1], argv[2], int(argv[3]))
    steps = 5
    out_path = None
    args = list(argv)
    while args:
        a = args.pop(0)
        if a == "--steps":
            steps = int(args.pop(0))
        elif a == "--out":
            out_path = args.pop(0)
    rows = []
    legs = list(STRATEGIES) + [
        ("ann:%s" % s, dict(STRATEGIES)[s]) for s in ANNOTATED]
    for strat, axes in legs:
        dump = tempfile.mkdtemp(
            prefix="mesh_dump_%s_" % strat.replace(":", "_"))
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=%d "
                      "--xla_dump_to=%s" % (N_DEV, dump))
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", strat,
             dump, str(steps)],
            env=env, capture_output=True, text=True, timeout=900)
        wall = time.time() - t0
        if proc.returncode != 0:
            rows.append({"strategy": strat, "axes": axes,
                         "error": (proc.stderr or proc.stdout)[-300:]})
            continue
        rec = json.loads(
            [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
        rec["axes"] = axes
        rec["total_s"] = round(wall, 1)
        rec.update({"collectives": _collectives_from_dump(dump)})
        rows.append(rec)
        print("%-12s %8.2f ms/step  %s" % (
            strat, rec["step_ms"],
            {k: v for k, v in rec["collectives"].items()
             if k not in ("module",)}), flush=True)
    md = _render(rows, steps)
    if out_path:
        with open(out_path, "w") as f:
            f.write(md)
        print("wrote %s" % out_path)
    else:
        print(md)
    return 0


def _render(rows, steps):
    lines = [
        "# MESH_PROFILE_r07 — per-strategy cost on the 8-device "
        "virtual CPU mesh",
        "",
        "Method: `tools/mesh_profile.py` — each strategy runs the same "
        "tiny dryrun-shaped program (transformer LM d128 L2 seq64 for "
        "dp/tp/sp/ep via ParallelExecutor; the 4-stage GPipe toy for "
        "pp) on an `--xla_force_host_platform_device_count=8` CPU "
        "mesh, timed over %d steps after a compile/warmup step.  The "
        "collective inventory is parsed from XLA's "
        "`after_optimizations` HLO dump of the step module — counts "
        "and payload bytes of all-reduce / all-gather / all-to-all / "
        "collective-permute.  Step wall on a host-thread-simulated "
        "mesh is indicative only; the collective inventory is exact "
        "compiler output and transfers to chips as-is.  NOTE: the "
        "batch size scales with dp (bs = 2*dp), so step wall is NOT "
        "comparable across strategies — only down a column (same "
        "strategy, r06 vs r07, legacy vs annotated)." % steps,
        "",
        "| strategy | mesh | step ms (CPU) | all-reduce | all-gather | "
        "all-to-all | collective-permute | coll. bytes/step |",
        "|---|---|---:|---:|---:|---:|---:|---:|",
    ]
    by_name = {}
    for r in rows:
        if "error" not in r:
            by_name[r["strategy"]] = r
        if r["strategy"].startswith("ann:"):
            continue  # annotated legs render in their own table
        if "error" in r:
            lines.append("| %s | `%s` | FAILED: %s |" % (
                r["strategy"], r["axes"], r["error"][:80]))
            continue
        c = r.get("collectives", {})
        lines.append(
            "| %s | `%s` | %.2f | %d | %d | %d | %d | %s |" % (
                r["strategy"], r["axes"], r["step_ms"],
                c.get("all-reduce", 0), c.get("all-gather", 0),
                c.get("all-to-all", 0), c.get("collective-permute", 0),
                "{:,}".format(c.get("bytes", 0))))
    lines += [
        "",
        "## Annotated lowering (ISSUE 20) vs hand-wired carriers",
        "",
        "The r07 addition: the same strategies lowered through "
        "`spmd.placement_for` + `apply_placement` — ShardingPass "
        "per-VarDesc annotations + the desc mesh stash, the executor "
        "inferring the mesh — instead of the hand `mesh_axes` carrier "
        "wiring.  Same program, same batch, same mesh; the annotated "
        "route must reproduce the legacy cost (ratio ~1.0) and the "
        "same collective inventory family.",
        "",
        "| strategy | legacy ms | annotated ms | ann/legacy | legacy "
        "colls (AR/AG/A2A/CP) | annotated colls |",
        "|---|---:|---:|---:|---|---|",
    ]

    def _cstr(c):
        return "%d/%d/%d/%d" % (
            c.get("all-reduce", 0), c.get("all-gather", 0),
            c.get("all-to-all", 0), c.get("collective-permute", 0))

    for name in ANNOTATED:
        leg, ann = by_name.get(name), by_name.get("ann:%s" % name)
        err = next((r for r in rows
                    if r["strategy"] == "ann:%s" % name
                    and "error" in r), None)
        if leg is None or ann is None:
            lines.append("| %s | %s | FAILED: %s | | | |" % (
                name, "%.2f" % leg["step_ms"] if leg else "?",
                (err or {}).get("error", "missing leg")[:80]))
            continue
        lines.append("| %s | %.2f | %.2f | %.3f | %s | %s |" % (
            name, leg["step_ms"], ann["step_ms"],
            ann["step_ms"] / leg["step_ms"],
            _cstr(leg.get("collectives", {})),
            _cstr(ann.get("collectives", {}))))
    ratios = [by_name["ann:%s" % n]["step_ms"] / by_name[n]["step_ms"]
              for n in ANNOTATED
              if by_name.get(n) and by_name.get("ann:%s" % n)]
    if ratios:
        lines += [
            "",
            "Verdict: ann/legacy spans %.3f–%.3f across %d strategies. "
            "Step wall on the host-thread mesh carries run-to-run noise "
            "well above the chip-relevant signal; the exact-compiler "
            "collective inventories are the ground truth, and they "
            "match family-for-family (the annotated tp legs trade "
            "all-gathers for all-reduces because GSPMD re-derives the "
            "partial-sum placement from annotations instead of the "
            "hand pairing, with FEWER total payload bytes)."
            % (min(ratios), max(ratios), len(ratios)),
        ]
    lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
