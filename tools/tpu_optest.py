"""Registry-wide TPU op sweep.

Parity: reference python/paddle/fluid/tests/unittests/op_test.py:261
(check_output_with_place) and :320 (check_output sweeping every available
place): the reference runs every op test on CPU *and* CUDA; this tool runs
every registered op on CPUPlace *and* TPUPlace (the real chip on this rig)
and holds the TPU result to the CPU result (the CPU path being the one the
full pytest suite validates numerically against references / finite
differences).

Three coverage modes, recorded per-op in the artifact:
  - "exact":      one-op program (tests/op_test.py harness) run on both
                  places, outputs allclose; for ops with `grad` in the spec
                  the analytic gradients (calc_gradient program) are compared
                  across places too.
  - "composite":  ops that only exist inside structured programs (While /
                  conditional_block / recurrent / TensorArray / LoD
                  plumbing): a full program is built with the fluid layers
                  front-end, run on both places, fetches compared; every op
                  type appearing in the program (+ its emitted grad ops) is
                  credited to that composite.
  - "skip":       host ops (OpInfo.host_op — the Executor runs them on the
                  host regardless of place, so there is no device lowering
                  to check) and the handful with a stated reason.

Stateful (PRNG) ops are compared exactly too: jax.random is counter-based
and platform-deterministic, so CPU and TPU must agree bit-for-bit modulo
float rounding.

Usage (driver):  TPU_OPTEST=1 python tools/tpu_optest.py
Writes TPU_OPTEST_r05.json at the repo root.  Without TPU_OPTEST=1 (or with
TPU_OPTEST_SELFCHECK=1) it compares CPUPlace against CPUPlace — a fast
validity check of every spec that needs no chip.
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.core.flags import FLAGS  # noqa: E402
from paddle_tpu.core import registry  # noqa: E402
from paddle_tpu.core.lod import LoDTensor  # noqa: E402
from paddle_tpu.core.types import np_dtype_to_proto  # noqa: E402
from paddle_tpu.core.scope import Scope  # noqa: E402
from op_test import OpTest  # noqa: E402

layers = fluid.layers
rng = np.random.RandomState(7)


def F(*shape):
    return rng.uniform(-1.0, 1.0, shape).astype(np.float32)


def P(*shape):
    return rng.uniform(0.5, 2.0, shape).astype(np.float32)


def I(shape, hi=5, lo=0):
    return rng.randint(lo, hi, shape).astype(np.int64)


def lodt(padded, lens):
    """LoDTensor from a padded [N,T,...] array + per-row lengths."""
    parts = [padded[i, :l] for i, l in enumerate(lens)]
    flat = np.concatenate(parts, 0)
    offs = np.concatenate([[0], np.cumsum(lens)]).tolist()
    return LoDTensor(flat, [offs])


# ---------------------------------------------------------------------------
# One-op specs.  inputs: slot -> array | LoDTensor | [(name, array), ...];
# outs: output slot names to fetch; grad: input names for the cross-place
# analytic-gradient check; tol: (atol, rtol) override.  The matmul-family
# default tolerance is loose because this host's CPU matmul runs reduced
# precision (see .claude/skills/verify/SKILL.md).
# ---------------------------------------------------------------------------

TOL = (1e-5, 1e-5)
TOL_MM = (2e-3, 2e-3)     # CPU reduced-precision matmul vs TPU
TOL_EXP = (1e-4, 1e-4)    # transcendental-heavy chains

SPECS = {}


def spec(op, inputs, attrs=None, outs=("Out",), grad=None, tol=TOL):
    SPECS[op] = dict(inputs=inputs, attrs=attrs or {}, outs=list(outs),
                     grad=grad, tol=tol)


# --- unary elementwise / activations ---
_UNARY_PLAIN = [
    "abs", "brelu", "ceil", "cos", "elu", "exp", "floor", "hard_shrink",
    "hard_sigmoid", "leaky_relu", "logsigmoid", "relu", "relu6", "round",
    "sigmoid", "sign", "sin", "soft_relu", "softplus", "softshrink",
    "softsign", "square", "stanh", "swish", "tanh", "tanh_shrink",
    "thresholded_relu", "fill_zeros_like", "isfinite",
]
for _op in _UNARY_PLAIN:
    _x = F(3, 5)
    _x[np.abs(_x) < 0.05] = 0.5   # stay off kinks for grad checks
    _info = registry._registry[_op]
    spec(_op, {"X": _x}, grad=None if _info.grad_maker is None else ["X"],
         tol=TOL_EXP)
for _op in ("log", "sqrt", "reciprocal"):
    spec(_op, {"X": P(3, 5)}, grad=["X"], tol=TOL_EXP)

spec("pow", {"X": P(3, 4)}, {"factor": 1.7}, grad=["X"], tol=TOL_EXP)
spec("scale", {"X": F(3, 4)}, {"scale": 2.5, "bias": 0.5}, grad=["X"])
spec("increment", {"X": F(1)}, {"step": 2.0})
spec("clip", {"X": F(3, 4)}, {"min": -0.4, "max": 0.4}, grad=["X"])
spec("clip_by_norm", {"X": F(3, 4)}, {"max_norm": 0.7}, tol=TOL_EXP)
spec("l1_norm", {"X": F(3, 4)}, grad=["X"])
spec("squared_l2_norm", {"X": F(3, 4)}, grad=["X"])
spec("mean", {"X": F(3, 4)}, grad=["X"])
spec("cumsum", {"X": F(3, 4)}, {"axis": 1, "exclusive": False,
                                "reverse": False}, grad=["X"])
spec("logical_not", {"X": I((3, 4), hi=2).astype(bool)})
spec("cast", {"X": F(3, 4)}, {"out_dtype": np_dtype_to_proto("int32")})
spec("softmax", {"X": F(4, 6)}, grad=["X"], tol=TOL_EXP)
spec("log_softmax", {"X": F(4, 6)}, {"axis": -1}, grad=["X"], tol=TOL_EXP)
spec("maxout", {"X": F(2, 6, 4, 4)}, {"groups": 2}, grad=["X"])

# --- binary elementwise + comparisons ---
for _op in ("elementwise_add", "elementwise_sub", "elementwise_mul",
            "elementwise_max", "elementwise_min"):
    spec(_op, {"X": F(3, 4), "Y": F(3, 4)}, {"axis": -1}, grad=["X", "Y"])
spec("elementwise_div", {"X": F(3, 4), "Y": P(3, 4)}, {"axis": -1},
     grad=["X", "Y"])
spec("elementwise_pow", {"X": P(3, 4), "Y": P(3, 4)}, {"axis": -1},
     tol=TOL_EXP)
spec("elementwise_mod", {"X": I((3, 4), hi=17, lo=1),
                         "Y": I((3, 4), hi=5, lo=1)})
spec("elementwise_floordiv", {"X": I((3, 4), hi=17, lo=1),
                              "Y": I((3, 4), hi=5, lo=1)})
spec("minus", {"X": F(3, 4), "Y": F(3, 4)}, grad=["X", "Y"])
for _op in ("equal", "not_equal", "less_than", "less_equal",
            "greater_than", "greater_equal"):
    spec(_op, {"X": I((3, 4), hi=3).astype(np.float32),
               "Y": I((3, 4), hi=3).astype(np.float32)})
for _op in ("logical_and", "logical_or", "logical_xor"):
    spec(_op, {"X": I((3, 4), hi=2).astype(bool),
               "Y": I((3, 4), hi=2).astype(bool)})

# --- reductions / indexing ---
for _op in ("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
            "reduce_prod"):
    spec(_op, {"X": P(3, 4, 5)}, {"dim": [1], "keep_dim": False,
                                  "reduce_all": False}, grad=["X"])
spec("arg_max", {"X": F(3, 5)}, {"axis": 1})
spec("arg_min", {"X": F(3, 5)}, {"axis": 1})
spec("argsort", {"X": F(3, 5)}, {"axis": 1}, outs=["Out", "Indices"])
spec("top_k", {"X": F(3, 6)}, {"k": 2}, outs=["Out", "Indices"])

# --- matmul family ---
spec("mul", {"X": F(4, 6), "Y": F(6, 3)},
     {"x_num_col_dims": 1, "y_num_col_dims": 1}, grad=["X", "Y"],
     tol=TOL_MM)
spec("matmul", {"X": F(2, 4, 6), "Y": F(2, 6, 3)},
     {"transpose_X": False, "transpose_Y": False, "alpha": 1.0},
     grad=["X", "Y"], tol=TOL_MM)
spec("bilinear_tensor_product",
     {"X": F(4, 3), "Y": F(4, 5), "Weight": F(2, 3, 5), "Bias": F(1, 2)},
     grad=["X", "Y", "Weight"], tol=TOL_MM)
spec("cos_sim", {"X": F(4, 5), "Y": F(4, 5)},
     outs=["Out", "XNorm", "YNorm"], grad=["X", "Y"], tol=TOL_EXP)
spec("conv_shift", {"X": F(3, 8), "Y": F(3, 3)}, grad=["X", "Y"],
     tol=TOL_MM)

# --- nn ---
spec("conv2d", {"Input": F(2, 3, 8, 8), "Filter": F(4, 3, 3, 3)},
     {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
      "groups": 1}, outs=["Output"], grad=["Input", "Filter"], tol=TOL_MM)
spec("depthwise_conv2d", {"Input": F(2, 4, 8, 8), "Filter": F(4, 1, 3, 3)},
     {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
      "groups": 4}, outs=["Output"], grad=["Input", "Filter"], tol=TOL_MM)
spec("conv2d_transpose", {"Input": F(2, 3, 6, 6), "Filter": F(3, 4, 3, 3)},
     {"strides": [2, 2], "paddings": [1, 1], "dilations": [1, 1]},
     outs=["Output"], grad=["Input", "Filter"], tol=TOL_MM)
spec("conv3d", {"Input": F(1, 2, 5, 6, 6), "Filter": F(3, 2, 3, 3, 3)},
     {"strides": [1, 1, 1], "paddings": [1, 1, 1],
      "dilations": [1, 1, 1], "groups": 1},
     outs=["Output"], grad=["Input", "Filter"], tol=TOL_MM)
spec("fused_conv2d_bn_act",
     # NHWC input, HWIO filter — the layout-pinned contract the fuse
     # pass (fluid/transpiler/layout_transpiler.py) emits; the explicit
     # grad lowering (residual-consuming, no forward re-run) is covered
     # through the forward spec's cross-place grad check
     {"Input": F(2, 8, 8, 3), "Filter": F(3, 3, 3, 4),
      "Scale": P(4), "Bias": F(4), "Mean": F(4) * 0.1, "Variance": P(4)},
     {"strides": [1, 1], "paddings": [1, 1], "epsilon": 1e-5,
      "momentum": 0.9, "is_test": False, "act": "relu",
      "data_format": "NHWC"},
     outs=["Y", "ConvOut", "MeanOut", "VarianceOut", "SavedMean",
           "SavedInvStd"],
     grad=["Input", "Filter", "Scale", "Bias"], tol=TOL_MM)
# --- fused transformer block stages (ISSUE 7) --- the explicit
# saved-activation grad lowerings are covered through each forward
# spec's cross-place grad check, like fused_conv2d_bn_act above
spec("gelu", {"X": F(3, 5)}, grad=["X"], tol=TOL_EXP)
spec("fused_matmul_bias_act",
     {"X": F(3, 4, 6), "W": F(6, 5), "Bias": F(5),
      "Residual": F(3, 4, 5)},
     {"x_num_col_dims": 2, "act": "gelu", "dropout_prob": 0.0},
     outs=["Out", "MulOut"], grad=["X", "W", "Bias", "Residual"],
     tol=TOL_MM)
spec("fused_qkv_matmul",
     {"X": F(3, 4, 6), "W": [("qkv_wq", F(6, 5)), ("qkv_wk", F(6, 5)),
                             ("qkv_wv", F(6, 4))]},
     {"x_num_col_dims": 2},
     outs=[("Out", 3)], grad=["X", "qkv_wq", "qkv_wv"], tol=TOL_MM)
spec("fused_add_ln",
     {"X": F(3, 4, 6), "Y": F(3, 4, 6), "Scale": P(6), "Bias": F(6)},
     {"begin_norm_axis": 2, "epsilon": 1e-5},
     outs=["Out", "Sum", "Mean", "Variance"],
     grad=["X", "Y", "Scale", "Bias"], tol=TOL_EXP)
spec("pool2d", {"X": F(2, 3, 8, 8)},
     {"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2],
      "paddings": [0, 0], "global_pooling": False, "exclusive": True,
      "adaptive": False}, grad=["X"])
spec("max_pool2d_with_index", {"X": F(2, 3, 8, 8)},
     {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]},
     outs=["Out", "Mask"], grad=["X"])
spec("unpool", {"X": F(2, 3, 4, 4),
                "Indices": np.tile(
                    (np.arange(16).reshape(4, 4) * 4 +
                     (np.arange(16).reshape(4, 4) // 4) * 8 % 4)[None, None],
                    (2, 3, 1, 1)).astype(np.int32) % 64},
     {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]})
spec("spp", {"X": F(2, 3, 8, 8)},
     {"pyramid_height": 2, "pooling_type": "max"}, grad=["X"])
spec("lrn", {"X": P(2, 6, 4, 4)},
     {"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75},
     outs=["Out", "MidOut"], grad=["X"], tol=TOL_EXP)
spec("batch_norm",
     {"X": F(4, 3, 5, 5), "Scale": P(3), "Bias": F(3),
      "Mean": F(3) * 0.1, "Variance": P(3)},
     {"epsilon": 1e-5, "momentum": 0.9, "is_test": False,
      "data_layout": "NCHW"},
     outs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
     grad=["X", "Scale", "Bias"], tol=TOL_EXP)
spec("layer_norm", {"X": F(4, 6), "Scale": P(6), "Bias": F(6)},
     {"begin_norm_axis": 1, "epsilon": 1e-5},
     outs=["Y", "Mean", "Variance"], grad=["X", "Scale", "Bias"],
     tol=TOL_EXP)
spec("norm", {"X": F(3, 4, 5)}, {"axis": 1, "epsilon": 1e-10},
     outs=["Out", "Norm"], grad=["X"], tol=TOL_EXP)
spec("row_conv", {"X": F(2, 6, 4), "Filter": F(3, 4)},
     grad=["X", "Filter"], tol=TOL_MM)
spec("im2sequence", {"X": F(2, 3, 6, 6)},
     {"kernels": [2, 2], "strides": [2, 2], "paddings": [0, 0, 0, 0]},
     grad=["X"], tol=TOL_MM)   # patches lower to conv on TPU
spec("dropout", {"X": P(4, 6)},
     {"dropout_prob": 0.5, "is_test": False,
      "dropout_implementation": "upscale_in_train"},
     outs=["Out", "Mask"])
spec("dropout_grad",
     {"Out@GRAD": [("out_grad", F(4, 6))], "Mask": [("mask", (
         rng.uniform(0, 1, (4, 6)) > 0.5).astype(np.float32))]},
     outs=["X@GRAD"])
spec("prelu", {"X": F(3, 4), "Alpha": P(1)}, {"mode": "all"},
     grad=["X", "Alpha"])

# --- losses ---
spec("cross_entropy",
     {"X": (lambda p: p / p.sum(1, keepdims=True))(P(4, 5)),
      "Label": I((4, 1), hi=5)},
     {"soft_label": False}, outs=["Y"], grad=["X"], tol=TOL_EXP)
spec("softmax_with_cross_entropy",
     {"Logits": F(4, 5), "Label": I((4, 1), hi=5)},
     {"soft_label": False}, outs=["Loss", "Softmax"], grad=["Logits"],
     tol=TOL_EXP)
spec("sigmoid_cross_entropy_with_logits",
     {"X": F(4, 5), "Label": rng.uniform(0, 1, (4, 5)).astype(np.float32)},
     grad=["X"], tol=TOL_EXP)
spec("hinge_loss", {"Logits": F(4, 1),
                    "Labels": I((4, 1), hi=2).astype(np.float32)},
     outs=["Loss"], grad=["Logits"])
spec("huber_loss", {"X": F(4, 1), "Y": F(4, 1)}, {"delta": 0.5},
     outs=["Out", "Residual"], grad=["X"])
spec("log_loss", {"Predicted": rng.uniform(0.1, 0.9, (4, 1)).astype(
    np.float32), "Labels": I((4, 1), hi=2).astype(np.float32)},
     {"epsilon": 1e-4}, outs=["Loss"], grad=["Predicted"], tol=TOL_EXP)
spec("modified_huber_loss", {"X": F(4, 1),
                             "Y": I((4, 1), hi=2).astype(np.float32)},
     outs=["Out", "IntermediateVal"], grad=["X"])
spec("rank_loss", {"Left": F(4, 1), "Right": F(4, 1),
                   "Label": I((4, 1), hi=2).astype(np.float32)},
     grad=["Left", "Right"], tol=TOL_EXP)
spec("margin_rank_loss", {"X1": F(4, 1), "X2": F(4, 1),
                          "Label": (I((4, 1), hi=2) * 2 - 1).astype(
                              np.float32)},
     {"margin": 0.1}, outs=["Out", "Activated"], grad=["X1", "X2"])
spec("smooth_l1_loss",
     {"X": F(4, 3), "Y": F(4, 3), "InsideWeight": P(4, 3),
      "OutsideWeight": P(4, 3)}, {"sigma": 1.0},
     outs=["Out", "Diff"], grad=["X"])
spec("squared_l2_distance", {"X": F(4, 3), "Y": F(4, 3)},
     outs=["Out", "sub_result"], grad=["X", "Y"])
spec("nce", {"Input": F(4, 6), "Label": I((4, 1), hi=20),
             "Weight": F(20, 6), "Bias": F(20)},
     {"num_total_classes": 20, "num_neg_samples": 5},
     outs=["Cost", "SampleLogits", "SampleLabels"], tol=TOL_MM)
spec("label_smooth", {"X": (lambda p: p / p.sum(1, keepdims=True))(P(4, 5)),
                      "PriorDist": [("prior", (lambda p: p / p.sum())(
                          P(1, 5)))]},
     {"epsilon": 0.1}, grad=["X"])

# --- optimizer ops (LearningRate is an extra input slot) ---
_LR = np.asarray([0.1], np.float32)
spec("sgd", {"Param": F(4, 3), "Grad": F(4, 3), "LearningRate": _LR},
     outs=["ParamOut"])
spec("momentum", {"Param": F(4, 3), "Grad": F(4, 3), "Velocity": F(4, 3),
                  "LearningRate": _LR}, {"mu": 0.9, "use_nesterov": False},
     outs=["ParamOut", "VelocityOut"])
spec("adam", {"Param": F(4, 3), "Grad": F(4, 3), "Moment1": F(4, 3) * 0.1,
              "Moment2": P(4, 3) * 0.1, "LearningRate": _LR,
              "Beta1Pow": np.asarray([0.9], np.float32),
              "Beta2Pow": np.asarray([0.999], np.float32)},
     {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
     outs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
           "Beta2PowOut"], tol=TOL_EXP)
spec("adamax", {"Param": F(4, 3), "Grad": F(4, 3), "Moment": F(4, 3) * 0.1,
                "InfNorm": P(4, 3), "LearningRate": _LR,
                "Beta1Pow": np.asarray([0.9], np.float32)},
     {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
     outs=["ParamOut", "MomentOut", "InfNormOut", "Beta1PowOut"],
     tol=TOL_EXP)
spec("adagrad", {"Param": F(4, 3), "Grad": F(4, 3), "Moment": P(4, 3) * 0.1,
                 "LearningRate": _LR}, {"epsilon": 1e-6},
     outs=["ParamOut", "MomentOut"], tol=TOL_EXP)
spec("decayed_adagrad",
     {"Param": F(4, 3), "Grad": F(4, 3), "Moment": P(4, 3) * 0.1,
      "LearningRate": _LR}, {"decay": 0.95, "epsilon": 1e-6},
     outs=["ParamOut", "MomentOut"], tol=TOL_EXP)
spec("adadelta",
     {"Param": F(4, 3), "Grad": F(4, 3), "AvgSquaredGrad": P(4, 3) * 0.1,
      "AvgSquaredUpdate": P(4, 3) * 0.1},
     {"rho": 0.95, "epsilon": 1e-6},
     outs=["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"],
     tol=TOL_EXP)
spec("rmsprop",
     {"Param": F(4, 3), "Grad": F(4, 3), "MeanSquare": P(4, 3) * 0.1,
      "Moment": F(4, 3) * 0.1, "LearningRate": _LR},
     {"decay": 0.9, "momentum": 0.9, "epsilon": 1e-6},
     outs=["ParamOut", "MeanSquareOut", "MomentOut"], tol=TOL_EXP)
spec("ftrl", {"Param": F(4, 3), "Grad": F(4, 3),
              "SquaredAccumulator": P(4, 3) * 0.1,
              "LinearAccumulator": F(4, 3) * 0.1, "LearningRate": _LR},
     {"l1": 0.1, "l2": 0.1, "lr_power": -0.5},
     outs=["ParamOut", "SquaredAccumOut", "LinearAccumOut"], tol=TOL_EXP)
spec("proximal_gd", {"Param": F(4, 3), "Grad": F(4, 3),
                     "LearningRate": _LR}, {"l1": 0.01, "l2": 0.01},
     outs=["ParamOut"], tol=TOL_EXP)
spec("proximal_adagrad",
     {"Param": F(4, 3), "Grad": F(4, 3), "Moment": P(4, 3) * 0.1,
      "LearningRate": _LR}, {"l1": 0.01, "l2": 0.01},
     outs=["ParamOut", "MomentOut"], tol=TOL_EXP)
spec("average_accumulates",
     {"Param": F(4, 3), "in_sum_1": F(4, 3), "in_sum_2": F(4, 3),
      "in_sum_3": F(4, 3),
      "in_num_accumulates": np.asarray([3], np.int64),
      "in_old_num_accumulates": np.asarray([2], np.int64),
      "in_num_updates": np.asarray([5], np.int64)},
     {"average_window": 0.15, "max_average_window": 10,
      "min_average_window": 2},
     outs=["out_sum_1", "out_sum_2", "out_sum_3", "out_num_accumulates",
           "out_old_num_accumulates", "out_num_updates"])

# --- tensor manipulation ---
spec("assign", {"X": F(3, 4)}, grad=["X"])
spec("assign_value", {}, {"shape": [2, 3], "dtype": np_dtype_to_proto("float32"),
                          "fp32_values": [float(v) for v in F(6)]})
spec("fill", {}, {"shape": [2, 3], "dtype": np_dtype_to_proto("float32"),
                  "value": [float(v) for v in F(6)]})
spec("fill_constant", {}, {"shape": [2, 3], "dtype": np_dtype_to_proto("float32"),
                           "value": 1.5})
spec("fill_constant_batch_size_like", {"Input": F(4, 3)},
     {"shape": [-1, 7], "dtype": np_dtype_to_proto("float32"), "value": 2.0,
      "input_dim_idx": 0, "output_dim_idx": 0})
spec("concat", {"X": [("cc_a", F(3, 2)), ("cc_b", F(3, 4))]}, {"axis": 1},
     grad=["cc_a", "cc_b"])
spec("sum", {"X": [("sm_a", F(3, 4)), ("sm_b", F(3, 4)),
                   ("sm_c", F(3, 4))]}, grad=["sm_a", "sm_b"])
spec("split", {"X": F(4, 6)}, {"axis": 1, "num": 2, "sections": []},
     outs=[("Out", 2)], grad=["X"])
spec("reshape", {"X": F(3, 4)}, {"shape": [2, 6]}, grad=["X"])
spec("reshape2", {"X": F(3, 4)}, {"shape": [2, 6]},
     outs=["Out", "XShape"], grad=["X"])
spec("squeeze", {"X": F(3, 1, 4)}, {"axes": [1]}, grad=["X"])
spec("unsqueeze", {"X": F(3, 4)}, {"axes": [1]}, grad=["X"])
spec("transpose", {"X": F(3, 4, 5)}, {"axis": [0, 2, 1]}, grad=["X"])
spec("transpose2", {"X": F(3, 4, 5)}, {"axis": [0, 2, 1]},
     outs=["Out", "XShape"], grad=["X"])
spec("reverse", {"X": F(3, 4)}, {"axis": [1]}, grad=["X"])
spec("expand", {"X": F(2, 3)}, {"expand_times": [2, 2]}, grad=["X"])
spec("pad", {"X": F(3, 4)}, {"paddings": [1, 1, 0, 2], "pad_value": 0.5},
     grad=["X"])
spec("crop", {"X": F(5, 6), "Y": F(3, 4)}, {"offsets": [1, 1]},
     grad=["X"])
spec("slice", {"Input": F(4, 6)},
     {"axes": [0, 1], "starts": [1, 2], "ends": [3, 5]}, grad=["Input"])
spec("gather", {"X": F(6, 3), "Index": I((4,), hi=6)}, grad=["X"])
spec("scatter", {"X": F(6, 3), "Ids": np.asarray([1, 3], np.int64),
                 "Updates": F(2, 3)}, grad=["X", "Updates"])
spec("one_hot", {"X": I((4, 1), hi=6)}, {"depth": 6})
spec("shape", {"Input": F(3, 4)})
spec("lookup_table", {"W": F(10, 4), "Ids": I((5, 1), hi=10)},
     {"padding_idx": -1}, grad=["W"])
spec("lookup_table_grad",
     {"W": F(10, 4), "Ids": I((5, 1), hi=10),
      "Out@GRAD": [("lt_og", F(5, 4))]},
     {"padding_idx": -1, "is_sparse": False}, outs=["W@GRAD"])
spec("multiplex", {"Ids": I((4, 1), hi=2),
                   "X": [("mx_a", F(4, 3)), ("mx_b", F(4, 3))]},
     grad=["mx_a", "mx_b"])
spec("bilinear_interp", {"X": F(2, 3, 4, 4)}, {"out_h": 8, "out_w": 8},
     grad=["X"])
spec("mean_iou", {"Predictions": I((8,), hi=4), "Labels": I((8,), hi=4)},
     {"num_classes": 4}, outs=["OutMeanIou", "OutWrong", "OutCorrect"])
spec("fake_dequantize_max_abs",
     {"X": I((3, 4), hi=127, lo=-127).astype(np.float32),
      "Scale": np.asarray([0.5], np.float32)}, {"max_range": 127.0})
spec("is_empty", {"X": F(2, 3)})

# --- metrics ---
spec("accuracy", {"Indices": I((4, 2), hi=5), "Label": I((4, 1), hi=5)},
     outs=["Accuracy", "Correct", "Total"])
spec("auc", {"Predict": rng.uniform(0, 1, (8, 2)).astype(np.float32),
             "Label": I((8, 1), hi=2),
             "TP": np.zeros(200, np.int64), "FP": np.zeros(200, np.int64),
             "TN": np.zeros(200, np.int64), "FN": np.zeros(200, np.int64)},
     {"num_thresholds": 200},
     outs=["AUC", "TPOut", "FPOut", "TNOut", "FNOut"])
spec("precision_recall",
     {"MaxProbs": rng.uniform(0, 1, (6, 1)).astype(np.float32),
      "Indices": I((6, 1), hi=3), "Labels": I((6, 1), hi=3),
      "Weights": P(6, 1), "StatesInfo": np.zeros((3, 4), np.float32)},
     {"class_number": 3},
     outs=["BatchMetrics", "AccumMetrics", "AccumStatesInfo"])

# --- random (stateful; jax PRNG is platform-deterministic) ---
spec("uniform_random", {}, {"shape": [4, 5], "min": -1.0, "max": 1.0,
                            "dtype": np_dtype_to_proto("float32")})
spec("gaussian_random", {}, {"shape": [4, 5], "mean": 0.0, "std": 1.0,
                             "dtype": np_dtype_to_proto("float32")})
spec("uniform_random_batch_size_like", {"Input": F(3, 2)},
     {"shape": [-1, 5], "min": -1.0, "max": 1.0, "dtype": np_dtype_to_proto("float32"),
      "input_dim_idx": 0, "output_dim_idx": 0})
spec("gaussian_random_batch_size_like", {"Input": F(3, 2)},
     {"shape": [-1, 5], "mean": 0.0, "std": 1.0, "dtype": np_dtype_to_proto("float32"),
      "input_dim_idx": 0, "output_dim_idx": 0})
spec("sampling_id", {"X": (lambda p: p / p.sum(1, keepdims=True))(P(4, 6))})
spec("random_crop", {"X": F(2, 3, 8, 8), "Seed": np.asarray([7], np.int64)},
     {"shape": [6, 6]}, outs=["Out"])

# --- sequence ops (LoD feeds) ---
_sq = F(3, 5, 4)
spec("sequence_pool", {"X": lodt(_sq, [5, 3, 2])}, {"pooltype": "SUM"},
     grad=["X"])
spec("sequence_softmax", {"X": lodt(F(3, 5, 1), [5, 3, 2])}, grad=["X"],
     tol=TOL_EXP)
spec("sequence_reshape", {"X": lodt(F(2, 4, 6), [4, 2])}, {"new_dim": 12})
spec("sequence_concat",
     {"X": [("sq_a", lodt(F(2, 4, 3), [4, 2])),
            ("sq_b", lodt(F(2, 3, 3), [2, 3]))]})
spec("sequence_erase", {"X": lodt(I((2, 5, 1), hi=6).astype(np.int64),
                                  [5, 4])}, {"tokens": [2, 3]})
spec("sequence_expand", {"X": F(2, 3), "Y": lodt(F(2, 5, 1), [2, 5])})
spec("sequence_slice", {"X": lodt(F(2, 5, 3), [5, 4]),
                        "Offset": np.asarray([[1], [0]], np.int64),
                        "Length": np.asarray([[2], [3]], np.int64)})
spec("sequence_conv", {"X": lodt(F(2, 6, 4), [6, 4]),
                       "Filter": F(3 * 4, 5)},
     {"contextLength": 3, "contextStart": -1},
     grad=["Filter"], tol=TOL_MM)
spec("lod_reset", {"X": lodt(F(2, 4, 3), [4, 2])},
     {"target_lod": [0, 2, 6]})
spec("gru", {"Input": lodt(F(2, 5, 9), [5, 3]), "Weight": F(3, 9),
             "H0": F(2, 3), "Bias": F(1, 9)},
     {"activation": "tanh", "gate_activation": "sigmoid",
      "is_reverse": False}, outs=["Hidden"], tol=TOL_MM)
spec("gru_unit", {"Input": F(4, 9), "HiddenPrev": F(4, 3),
                  "Weight": F(3, 9), "Bias": F(1, 9)},
     {"activation": "tanh", "gate_activation": "sigmoid"},
     outs=["Hidden", "Gate", "ResetHiddenPrev"],
     grad=["Input", "HiddenPrev", "Weight"], tol=TOL_MM)
spec("lstm", {"Input": lodt(F(2, 5, 12), [5, 3]), "Weight": F(3, 12),
              "Bias": F(1, 12), "H0": F(2, 3), "C0": F(2, 3)},
     outs=["Hidden", "Cell"], tol=TOL_MM)
spec("lstm_unit", {"X": F(4, 12), "C_prev": F(4, 3)},
     {"forget_bias": 0.0}, outs=["C", "H"],
     grad=["X", "C_prev"], tol=TOL_EXP)
spec("lstmp", {"Input": lodt(F(2, 5, 12), [5, 3]), "Weight": F(2, 12),
               "ProjWeight": F(3, 2), "Bias": F(1, 12),
               "H0": F(2, 2), "C0": F(2, 3)},
     {"proj_activation": "tanh"}, outs=["Projection", "Cell"], tol=TOL_MM)
spec("edit_distance",
     {"Hyps": lodt(I((2, 4, 1), hi=6), [4, 3]),
      "Refs": lodt(I((2, 4, 1), hi=6), [3, 4])},
     {"normalized": False}, outs=["Out", "SequenceNum"])
spec("seq_cross_attention",
     {"Q": lodt(F(2, 4, 6), [4, 3]), "K": lodt(F(2, 5, 6), [5, 2]),
      "V": lodt(F(2, 5, 6), [5, 2])}, {},
     grad=["Q", "K", "V"], tol=TOL_MM)

def lodt2(n_inner, width, dim):
    """Level-2 LoDTensor: outer offsets over inner seqs, inner over
    tokens."""
    rng2 = np.random.RandomState(3)
    inner_lens = [rng2.randint(1, width + 1) for _ in range(sum(n_inner))]
    total = sum(inner_lens)
    data = rng2.randn(total, dim).astype(np.float32)
    inner_offs = np.concatenate([[0], np.cumsum(inner_lens)]).tolist()
    outer_offs = np.concatenate([[0], np.cumsum(n_inner)]).tolist()
    return LoDTensor(data, [outer_offs, inner_offs])


spec("sub_nested_seq",
     {"X": lodt2([2, 3], 4, 3),
      "SelectedIndices": lodt(I((2, 2, 1), hi=2), [1, 2])},
     grad=["X"])

spec("scale_sub_region",
     {"X": F(2, 3, 4, 4),
      "Indices": np.asarray([[1, 2, 1, 3, 2, 4], [2, 3, 2, 2, 1, 1]],
                            np.int64)},
     {"value": 2.0}, grad=["X"])

spec("kmax_seq_score", {"X": lodt(F(2, 6, 1), [6, 3])},
     {"beam_size": 2})

spec("lambda_rank",
     {"Score": lodt(F(2, 5, 1), [5, 3]),
      "Label": lodt(I((2, 5, 1), hi=3).astype(np.float32), [5, 3])},
     {"NDCG_num": 3}, grad=["Score"], tol=TOL_EXP)

# --- CRF / CTC ---
spec("linear_chain_crf",
     {"Emission": lodt(F(2, 5, 4), [5, 3]),
      "Label": lodt(I((2, 5, 1), hi=4), [5, 3]),
      "Transition": F(6, 4)},
     outs=["LogLikelihood"], grad=["Emission", "Transition"], tol=TOL_EXP)
spec("crf_decoding",
     {"Emission": lodt(F(2, 5, 4), [5, 3]), "Transition": F(6, 4)},
     outs=["ViterbiPath"])
spec("warpctc",
     {"Logits": lodt(F(2, 6, 5), [6, 5]),
      "Label": lodt(I((2, 3, 1), hi=4, lo=1), [3, 2])},
     {"blank": 0, "norm_by_times": False},
     outs=["Loss"], grad=["Logits"], tol=TOL_EXP)
spec("ctc_align", {"Input": lodt(I((2, 6, 1), hi=4), [6, 5])},
     {"blank": 0, "padding_value": 0}, outs=["Output"])

# --- detection ---
spec("iou_similarity", {"X": rng.uniform(0, 10, (4, 4)).astype(np.float32),
                        "Y": rng.uniform(0, 10, (5, 4)).astype(np.float32)})
spec("box_coder",
     {"PriorBox": rng.uniform(0, 10, (5, 4)).astype(np.float32),
      "PriorBoxVar": P(5, 4) * 0.1,
      "TargetBox": rng.uniform(-1, 1, (3, 5, 4)).astype(np.float32)},
     {"code_type": "decode_center_size"}, outs=["OutputBox"], tol=TOL_EXP)
spec("prior_box", {"Input": F(1, 3, 4, 4), "Image": F(1, 3, 32, 32)},
     {"min_sizes": [4.0], "max_sizes": [8.0], "aspect_ratios": [2.0],
      "flip": True, "clip": True, "variances": [0.1, 0.1, 0.2, 0.2],
      "offset": 0.5, "step_w": 0.0, "step_h": 0.0},
     outs=["Boxes", "Variances"])
spec("bipartite_match",
     {"DistMat": rng.uniform(0, 1, (2, 3, 6)).astype(np.float32)},
     {"match_type": "per_prediction", "dist_threshold": 0.5},
     outs=["ColToRowMatchIndices", "ColToRowMatchDist"])
spec("mine_hard_examples",
     {"ClsLoss": rng.uniform(0, 2, (2, 8)).astype(np.float32),
      "MatchIndices": np.asarray([[0, -1, -1, 1, -1, -1, -1, -1],
                                  [-1, 0, -1, -1, -1, 1, -1, -1]],
                                 np.int64)},
     {"mining_type": "max_negative", "neg_pos_ratio": 2.0,
      "sample_size": -1}, outs=["NegIndices", "UpdatedMatchIndices"])
spec("target_assign",
     {"X": F(2, 3, 4),
      "MatchIndices": np.asarray([[0, -1, 2, -1], [1, -1, -1, 0]],
                                 np.int64)},
     {"mismatch_value": 0}, outs=["Out", "OutWeight"])
spec("gather_encoded_target",
     {"Encoded": F(2, 3, 4, 4),
      "MatchIndices": np.asarray([[0, -1, 2, -1], [1, -1, -1, 0]],
                                 np.int64)},
     outs=["Out", "OutWeight"])
spec("polygon_box_transform", {"Input": F(1, 4, 3, 3)}, outs=["Output"])
spec("roi_pool",
     {"X": F(1, 2, 8, 8),
      "ROIs": np.asarray([[0, 1, 1, 5, 5], [0, 2, 2, 7, 7]], np.float32)},
     {"pooled_height": 2, "pooled_width": 2, "spatial_scale": 1.0},
     outs=["Out", "Argmax"])

# --- parallel / kernels (dense single-place paths) ---
spec("ring_attention", {"Q": F(2, 2, 8, 4), "K": F(2, 2, 8, 4),
                        "V": F(2, 2, 8, 4)}, {"causal": True},
     grad=["Q", "K", "V"], tol=TOL_MM)
spec("moe_ffn", {"X": F(6, 4), "RouterW": F(4, 2), "W1": F(2, 4, 8),
                 "W2": F(2, 8, 4)}, {"capacity_factor": 2.0},
     grad=["X", "W1", "W2"], tol=TOL_MM)
spec("sharding_constraint", {"X": F(4, 4)}, {"spec": ("dp", None)},
     grad=["X"])

# --- beam search (one-op device form; cf. tests/test_beam_search.py) ---
spec("beam_search",
     {"pre_ids": I((4, 1), hi=5, lo=1),
      "pre_scores": rng.uniform(-2, 0, (4, 1)).astype(np.float32),
      "ids": I((4, 6), hi=6),
      "scores": np.log((lambda p: p / p.sum(1, keepdims=True))(
          P(4, 6))).astype(np.float32)},
     {"beam_size": 2, "end_id": 0},
     outs=["selected_ids", "selected_scores", "parent_idx"])

SKIPS = {
    "beam_search_decode": "host-side trace reconstruction over per-step "
                          "host arrays (covered by tests/test_beam_search.py"
                          " and the v2 generation workflow test)",
}


# ---------------------------------------------------------------------------
# Justified-refusal ledger — the op-parity TAIL, closed explicitly.
#
# Every v2 surface that deliberately raises NotImplementedError is
# enumerated here with its justification and the supported route.  The
# artifact carries the ledger verbatim, and tests/test_refusal_ledger.py
# asserts the set of in-tree NotImplementedError guards equals this set,
# so the tail cannot grow (or rot) silently: adding a new refusal without
# a ledger entry — or listing one that no longer exists — fails the suite.
#
# kind="refusal": the whole v2 symbol is refused (callable exists for
# source compatibility; every call raises).  kind="partial": the layer IS
# ported and a specific argument/mode raises; ``param`` names it.
# ---------------------------------------------------------------------------

REFUSALS = {
    # -- whole-symbol refusals (3) --
    "get_output": dict(
        kind="refusal",
        reason="layers here have exactly one output value; auxiliary "
               "outputs ride as attributes (e.g. lstm_step(...).state)",
        use=".state attribute / fluid.layers"),
    "cross_entropy_over_beam": dict(
        kind="refusal",
        reason="beam-training (CRF-over-beam) requires the gserver beam "
               "expansion records, which the XLA lowering never builds",
        use="layer.beam_search for generation + per-step "
            "cross_entropy_cost for training"),
    "SubsequenceInput": dict(
        kind="refusal",
        reason="nested-sequence (level-2) recurrent_group: level-k LoD "
               "data is ported but the scan-over-subsequences control "
               "form is not",
        use="fluid.layers.sequence_* on the inner level, or seq_reshape"),
    # -- partial guards: the layer works, one argument/mode refuses --
    "context_projection": dict(
        kind="partial", param="padding_attr",
        reason="trainable context padding is a gserver parameter; zero "
               "padding (padding_attr=False) is the ported semantics",
        use="padding_attr=False"),
    "conv_operator": dict(
        kind="partial", param="trans / per-sample kernels",
        reason="transposed variant and reference ConvOperator's "
               "per-sample kernel stream have no grouped-conv lowering",
        use="conv_projection(trans=True) / img_conv_layer"),
    "seq_reshape": dict(
        kind="partial", param="bias_attr",
        reason="reshape is data movement; the reference bias add after "
               "it is not ported",
        use="seq_reshape(...) + layer.addto with a bias layer"),
    "selective_fc": dict(
        kind="partial", param="select",
        reason="column selection is a gserver execution optimization; "
               "the full fc computes identical selected values",
        use="select=None (full fc)"),
    "upsample": dict(
        kind="partial", param="mask-free / upsample_size / pad_out_*",
        reason="needs the paired max-pool mask; explicit output sizing "
               "is not ported (output is scale * input)",
        use="bilinear_interp for mask-free interpolation"),
    "img_conv3d": dict(
        kind="partial", param="trans",
        reason="transposed 3-D convolution has no lowering",
        use="img_conv3d(trans=False)"),
    "prelu": dict(
        kind="partial", param="partial_sum>1",
        reason="per-group alpha sharing is not ported",
        use="partial_sum=1 (per-element) or channel_shared=True"),
    "sub_seq": dict(
        kind="partial", param="bias_attr",
        reason="subsequence extraction is data movement; the post-slice "
               "bias is not ported",
        use="sub_seq(...) + layer.addto"),
    "lstm_step": dict(
        kind="partial", param="gate/state activations",
        reason="the lstm_unit op fixes the standard tanh/sigmoid gate "
               "math; non-default step activations are not ported",
        use="default activations"),
    "multibox_loss": dict(
        kind="partial", param="label / neg_overlap",
        reason="the v1 packed-label stream and the mining op's "
               "negative-overlap threshold are not ported",
        use="(gt_box, gt_label) layers; tune neg_pos_ratio"),
    "nce": dict(
        kind="partial", param="neg_distribution / weight / multi-input",
        reason="only the uniform sampler is ported; per-example "
               "weighting and implicit multi-input concat are not",
        use="uniform sampler; layer.scaling; concat inputs first"),
    "hsigmoid": dict(
        kind="partial", param="multi-input",
        reason="implicit multi-input concat is not ported",
        use="concat inputs first"),
    "lambda_cost": dict(
        kind="partial", param="max_sort_size",
        reason="partial-sort truncation is a CPU-side optimization; the "
               "whole candidate list is ranked",
        use="default (full ranking)"),
}


# ---------------------------------------------------------------------------
# Composite programs: build with the fluid front-end, run on both places,
# compare every fetch; credit every op type in the program (fwd + emitted
# grad ops) to the composite.
# ---------------------------------------------------------------------------

def _run_program(build, place):
    main = fluid.Program()
    startup = fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                feed, fetch_list = build()
        exe = fluid.Executor(place)
        exe.run(startup)
        outs = exe.run(main, feed=feed, fetch_list=fetch_list)
    op_types = set()

    def _collect(block):
        for op in block.ops:
            op_types.add(op.type)
            sub = op.attr("sub_block")
            if sub is not None:
                _collect(main.block(sub) if isinstance(sub, int) else sub)

    for block in main.blocks:
        _collect(block)
    return [np.asarray(o) for o in outs], op_types


def composite_while_array():
    """While + TensorArray: while, create_array, write_to_array,
    read_from_array, lod_array_length, increment, less_than."""
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=5)
    x = layers.fill_constant(shape=[3], dtype="float32", value=1.0)
    arr = layers.create_array("float32", element_shape=[3], capacity=8)
    cond = layers.less_than(x=i, y=n)
    w = layers.While(cond=cond)
    with w.block():
        xi = layers.scale(x=x, scale=2.0)
        layers.array_write(xi, i, array=arr)
        layers.increment(x=i, value=1.0, in_place=True)
        layers.less_than(x=i, y=n, cond=cond)
    j = layers.fill_constant(shape=[1], dtype="int64", value=3)
    read = layers.array_read(arr, j)
    length = layers.array_length(arr)
    return {}, [read, length]


def composite_ifelse():
    """IfElse: conditional_block, split_lod_tensor, merge_lod_tensor."""
    x = layers.data(name="ie_x", shape=[4], dtype="float32")
    zero = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    row_sum = layers.reduce_sum(x, dim=1, keep_dim=True)
    cond = layers.greater_than(row_sum, zero)
    ie = layers.IfElse(cond)
    with ie.true_block():
        xt = ie.input(x)
        ie.output(layers.scale(xt, scale=3.0))
    with ie.false_block():
        xf = ie.input(x)
        ie.output(layers.scale(xf, scale=-1.0))
    pred = ie()
    xv = np.random.RandomState(3).randn(6, 4).astype(np.float32)
    return {"ie_x": xv}, [pred]


def composite_dynrnn():
    """DynamicRNN: recurrent, lod_rank_table, lod_tensor_to_array,
    array_to_lod_tensor, max_sequence_len, shrink_rnn_memory, ..."""
    x = layers.data(name="dr_x", shape=[3], dtype="float32", lod_level=1)
    rnn = layers.DynamicRNN()
    with rnn.block():
        x_t = rnn.step_input(x)
        h = rnn.memory(shape=[3], batch_ref=x, init_value=0.0)
        h_new = layers.elementwise_add(x=h, y=x_t)
        rnn.update_memory(h, h_new)
        rnn.output(h_new)
    out = rnn()
    final = rnn.final_states[0]
    padded = np.random.RandomState(4).randn(3, 4, 3).astype(np.float32)
    feed = {"dr_x": lodt(padded, [4, 2, 3])}
    return feed, [out, final]


def composite_lod_array_round_trip():
    """lod_rank_table + lod_tensor_to_array + array_to_lod_tensor +
    max_sequence_len + reorder_lod_tensor_by_rank + shrink_rnn_memory."""
    x = layers.data(name="rt_x", shape=[2], dtype="float32", lod_level=1)
    table = layers.lod_rank_table(x)
    arr = layers.lod_tensor_to_array(x, table)
    back = layers.array_to_lod_tensor(arr, table)
    mlen = layers.max_sequence_len(table)
    reordered = layers.reorder_lod_tensor_by_rank(x, table)
    i0 = layers.fill_constant(shape=[1], dtype="int64", value=0)
    shrunk = layers.shrink_memory(back, i0, table)
    feed = {"rt_x": lodt(np.random.RandomState(5).randn(2, 3, 2)
                         .astype(np.float32), [3, 2])}
    return feed, [back, mlen, reordered, shrunk]


def composite_conditional_block():
    """ConditionalBlock (conditional_block op) scalar gating."""
    flag = layers.data(name="cb_flag", shape=[1], dtype="float32",
                       append_batch_size=False)
    zero = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    out = layers.fill_constant(shape=[1], dtype="float32", value=-1.0)
    cond = layers.greater_than(flag, zero)
    cb = layers.ConditionalBlock([cond])
    with cb.block():
        v = layers.scale(x=flag, scale=10.0)
        layers.assign(v, out)
    return {"cb_flag": np.asarray([3.0], np.float32)}, [out]


def composite_select():
    """In-program CSP select (ISSUE 8 parity rider; reference
    operators/select_op.cc): channel_create + go producer +
    channel_send + select(recv|recv) + the device consumer of the
    received value.  Credits: select, channel_create, channel_send,
    go."""
    from paddle_tpu.fluid import concurrency as C

    x = layers.data(name="sel_x", shape=[3], dtype="float32")
    ch_idle = C.program_make_channel(dtype="float32", capacity=1)
    ch_live = C.program_make_channel(dtype="float32", capacity=1)
    with C.ProgramGo():
        C.program_channel_send(ch_live, layers.scale(x, scale=2.0))
    got_a = layers.data(name="sel_got_a", shape=[3], dtype="float32")
    got_b = layers.data(name="sel_got_b", shape=[3], dtype="float32")
    idx = C.program_select([("recv", ch_idle, got_a),
                            ("recv", ch_live, got_b)], timeout=10.0)
    out = layers.scale(got_b, scale=10.0)
    xv = np.random.RandomState(6).randn(2, 3).astype(np.float32)
    return {"sel_x": xv}, [idx, out]


COMPOSITES = {
    "while_array": composite_while_array,
    "ifelse": composite_ifelse,
    "dynrnn": composite_dynrnn,
    "lod_array_round_trip": composite_lod_array_round_trip,
    "conditional_block": composite_conditional_block,
    "select": composite_select,
}


# ---------------------------------------------------------------------------
# Runners
# ---------------------------------------------------------------------------

def _make_optest(op, s):
    t = OpTest()
    t.op_type = op
    t.inputs = s["inputs"]
    t.attrs = s["attrs"]
    outs = {}
    for o in s["outs"]:
        if isinstance(o, tuple):   # multi-output slot: (slot, count)
            slot, cnt = o
            outs[slot] = [("%s_%s_%d" % (op, slot.lower(), k),
                           np.zeros(1, np.float32)) for k in range(cnt)]
        else:
            outs[o] = np.zeros(1, np.float32)
    t.outputs = outs
    return t


def _fetch_names(t):
    names = []
    for slot, val in t.outputs.items():
        entries = val if isinstance(val, list) else [(slot, val)]
        names.extend(n for n, _ in entries)
    return names


def _compare(name, a, b, atol, rtol):
    a = np.asarray(a)
    b = np.asarray(b)
    if a.shape != b.shape:
        return "shape mismatch %s: %s vs %s" % (name, a.shape, b.shape)
    if a.dtype.kind in "iub":
        if not np.array_equal(a, b):
            return "int mismatch %s: %d differing" % (
                name, int((a != b).sum()))
        return None
    err = np.abs(a.astype(np.float64) - b.astype(np.float64))
    denom = np.maximum(np.abs(a).astype(np.float64), 1.0)
    if not (err <= atol + rtol * denom).all():
        return "float mismatch %s: max_abs %.3e max_rel %.3e" % (
            name, err.max(), (err / denom).max())
    return None


def _grad_program(t, wrt):
    """Build the one-op program + scalar head + calc_gradient; returns
    (main, startup, feed, grad_names)."""
    main, startup, feed = t._build()
    grng = np.random.RandomState(11)
    with fluid.program_guard(main, startup):
        block = main.global_block()
        parts = []
        for oname in t._first_float_outputs():
            ovar = block.var(oname)
            w = grng.uniform(0.5, 1.5, [int(d) for d in ovar.shape]
                             ).astype(np.float32)
            wvar = layers.assign(w)
            wvar.stop_gradient = True
            parts.append(layers.reduce_sum(
                layers.elementwise_mul(ovar, wvar)))
        head = parts[0] if len(parts) == 1 else layers.sums(parts)
        loss = layers.reduce_sum(head)
        grads = fluid.backward.calc_gradient(
            loss, [block.var(n) for n in wrt])
    return main, startup, feed, [g.name for g in grads]


def _run_on(place, main, feed, fetch_names):
    exe = fluid.Executor(place)
    scope = Scope()
    with fluid.scope_guard(scope):
        return exe.run(main, feed=feed, fetch_list=fetch_names)


def run_exact(op, s, cpu, dev):
    # Matmul-family ops are checked at the exact-f32 precision contract:
    # the TPU backend's DEFAULT multiplies f32 in bf16 passes (measured
    # 3e-3..4e-2 rel vs an f64 oracle on which the CPU backend sits at
    # ~1e-7), so the check pins FLAGS.matmul_precision='highest' — the
    # documented knob (MIGRATION.md) — and holds the chip to ~1e-4.
    exact_f32 = s["tol"] is TOL_MM
    prev = FLAGS.matmul_precision
    if exact_f32:
        FLAGS.matmul_precision = "highest"
    try:
        return _run_exact_inner(op, s, cpu, dev)
    finally:
        if exact_f32:
            FLAGS.matmul_precision = prev


def _run_exact_inner(op, s, cpu, dev):
    t = _make_optest(op, s)
    names = _fetch_names(t)
    atol, rtol = s["tol"]
    ref = t.run_outputs(cpu, fetch_names=names)
    got = t.run_outputs(dev, fetch_names=names)
    errs = [e for e in (_compare(n, ref[n], got[n], atol, rtol)
                        for n in names) if e]
    grad_checked = False
    if s["grad"]:
        # Grad heads need true output shapes for the weight tensors:
        # rebuild with declared shapes from the CPU run.
        t2 = _make_optest(op, s)
        outs2 = {}
        for slot, val in t.outputs.items():
            entries = val if isinstance(val, list) else [(slot, val)]
            outs2[slot] = [(n, ref[n]) for n, _ in entries] \
                if isinstance(val, list) else ref[entries[0][0]]
        t2.outputs = outs2
        main, startup, feed, gnames = _grad_program(t2, s["grad"])
        g_ref = _run_on(cpu, main, feed, gnames)
        g_dev = _run_on(dev, main, feed, gnames)
        for wname, a, b in zip(s["grad"], g_ref, g_dev):
            e = _compare("d/d%s" % wname, a, b,
                         max(atol, 1e-3), max(rtol, 1e-3))
            if e:
                errs.append(e)
        grad_checked = True
    return errs, grad_checked


def main():
    on_tpu = os.environ.get("TPU_OPTEST") == "1" and not \
        os.environ.get("TPU_OPTEST_SELFCHECK")
    cpu = fluid.CPUPlace()
    dev = fluid.TPUPlace() if on_tpu else fluid.CPUPlace()
    dev_desc = repr(dev.jax_device()) if on_tpu else "cpu-selfcheck"
    only = sys.argv[1:]  # optional op-name filter for debugging

    results = {}
    t_start = time.time()

    # 1) composites first (their credit list gates the skip accounting)
    composite_credit = {}
    for cname, build in COMPOSITES.items():
        if only and cname not in only:
            continue
        try:
            ref, ops_ref = _run_program(build, cpu)
            got, _ = _run_program(build, dev)
            errs = [e for e in (_compare("%s[%d]" % (cname, i), a, b,
                                         1e-4, 1e-4)
                                for i, (a, b) in enumerate(zip(ref, got)))
                    if e]
            status = "pass" if not errs else "fail"
            note = "; ".join(errs)
        except Exception as exc:  # noqa: BLE001 — triaged into the artifact
            status, note, ops_ref = "fail", "%s: %s" % (
                type(exc).__name__, exc), set()
            traceback.print_exc()
        for o in ops_ref:
            composite_credit.setdefault(o, []).append((cname, status, note))
        print("[composite %-22s] %s %s" % (cname, status, note))

    ops = registry.registered_ops()
    for op in ops:
        if only and op not in only:
            continue
        info = registry._registry[op]
        if info.host_op:
            results[op] = dict(
                status="skip", mode="host",
                note="host op: executed by the Executor on the host "
                     "regardless of place (no device lowering to check)")
            continue
        if op in SPECS:
            s = SPECS[op]
            t0 = time.time()
            try:
                errs, grad_checked = run_exact(op, s, cpu, dev)
                status = "pass" if not errs else "fail"
                results[op] = dict(
                    status=status, mode="exact",
                    atol=s["tol"][0], rtol=s["tol"][1],
                    precision=("highest" if s["tol"] is TOL_MM
                               else "default"),
                    grad_checked=grad_checked,
                    seconds=round(time.time() - t0, 2),
                    note="; ".join(errs))
            except Exception as exc:  # noqa: BLE001
                results[op] = dict(
                    status="fail", mode="exact",
                    seconds=round(time.time() - t0, 2),
                    note="%s: %s" % (type(exc).__name__, exc))
                traceback.print_exc()
            print("[%-34s] %s %s" % (op, results[op]["status"],
                                     results[op].get("note", "")[:120]))
        elif op in composite_credit:
            entries = composite_credit[op]
            status = ("pass" if all(s == "pass" for _, s, _ in entries)
                      else "fail")
            results[op] = dict(
                status=status, mode="composite",
                via=[c for c, _, _ in entries],
                note="; ".join(n for _, s, n in entries if n))
        elif op in SKIPS:
            results[op] = dict(status="skip", mode="declared",
                               note=SKIPS[op])
        else:
            results[op] = dict(status="fail", mode="unspecced",
                               note="no spec, no composite credit")

    # registered <op>_grad entries are exercised by the forward spec's
    # grad check (run_exact compares analytic gradients), so they carry
    # the forward op's verdict instead of counting as unspecced
    for op, r in results.items():
        if r["mode"] != "unspecced" or not op.endswith("_grad"):
            continue
        fwd = results.get(op[:-5])
        if fwd is not None and fwd.get("grad_checked"):
            results[op] = dict(
                status=fwd["status"], mode="grad-of-spec",
                via=op[:-5],
                note="checked by %s's grad comparison" % op[:-5])

    if not only:
        npass = sum(1 for r in results.values() if r["status"] == "pass")
        nskip = sum(1 for r in results.values() if r["status"] == "skip")
        nfail = len(results) - npass - nskip
        ngrad = sum(1 for r in results.values() if r.get("grad_checked"))
        artifact = dict(
            meta=dict(
                device=dev_desc,
                oracle="CPUPlace (full pytest suite validates this path "
                       "against references / finite differences)",
                precision_note="ops with precision='highest' pin "
                               "FLAGS.matmul_precision for the check: "
                               "the TPU default multiplies f32 in bf16 "
                               "passes (fast mode, 3e-3..4e-2 rel); "
                               "'highest' is the exact-f32 contract — "
                               "see MIGRATION.md",
                grad_note="grad_checked ops compare the TPU analytic "
                          "gradient (calc_gradient program) against the "
                          "CPU analytic gradient",
                date=time.strftime("%Y-%m-%d %H:%M:%S"),
                total_ops=len(results), passed=npass, failed=nfail,
                skipped=nskip, grad_checked=ngrad,
                refused=sum(1 for r in REFUSALS.values()
                            if r["kind"] == "refusal"),
                partial_guards=sum(1 for r in REFUSALS.values()
                                   if r["kind"] == "partial"),
                wall_seconds=round(time.time() - t_start, 1)),
            refusal_ledger=REFUSALS,
            results=results)
        out = os.path.join(REPO, "TPU_OPTEST_r05.json")
        with open(out, "w") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
        print("\n%d ops: %d pass, %d fail, %d skip (%d grad-checked) "
              "on %s in %.0fs -> %s" %
              (len(results), npass, nfail, nskip, ngrad, dev_desc,
               time.time() - t_start, out))
        return 1 if nfail else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
