"""Fused conv-stage kernel sweep at the ResNet-50 bs256 stage shapes.

flash_tune.py's method applied to ISSUE 5 lever (b): for each distinct
conv+BN+ReLU stage of the headline model, measure fwd wall time of

  nchw    — lax conv NCHW/OIHW + BN(batch stats)+relu, XLA-fused
            (the round-4 baseline the byte floor was measured on),
  nhwc    — same math, NHWC/HWIO operands (lever a alone), and
  fused   — the Pallas conv-stage kernel with in-kernel BN statistics
            (kernels/conv_fused.py; lever a + b),

with the microbench traps handled: distinct pre-staged inputs, unrolled
chain, one final d2h drain.  On the real chip the per-kernel xplane
attribution for PROFILE_r06.md comes from wrapping this in
``jax.profiler.trace`` (CONV_TUNE_PROFILE=<dir>).

Usage: python tools/conv_tune.py [steps] [batch]
"""
from __future__ import annotations

import contextlib
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.kernels import conv_fused  # noqa: E402

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 8
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 256

# (name, h, ci, co, k, stride, pad) — the distinct ResNet-50 stage
# shapes (each repeats across blocks; counts in the comment)
STAGES = [
    ("stem7x7s2", 224, 3, 64, 7, 2, 3),       # x1
    ("r1_1x1", 56, 64, 64, 1, 1, 0),          # bottleneck reduce
    ("r1_3x3", 56, 64, 64, 3, 1, 1),          # x3
    ("r1_expand", 56, 64, 256, 1, 1, 0),
    ("r2_3x3", 28, 128, 128, 3, 1, 1),        # x4
    ("r2_down", 56, 256, 512, 1, 2, 0),       # shortcut downsample
    ("r3_3x3", 14, 256, 256, 3, 1, 1),        # x6
    ("r4_3x3", 7, 512, 512, 3, 1, 1),         # x3
]


def _bn_relu(y, eps=1e-5):
    """Batch-stats BN + relu on an NHWC (or NCHW via axis) conv out —
    the elementwise tail XLA fuses either way."""
    red = tuple(range(y.ndim - 1))
    yf = y.astype(jnp.float32)
    mean = yf.mean(axis=red)
    var = jnp.square(yf).mean(axis=red) - jnp.square(mean)
    inv = jax.lax.rsqrt(var + eps)
    return jnp.maximum((yf - mean) * inv, 0.0).astype(y.dtype)


def bench_stage(name, h, ci, co, k, s, p, dtype=jnp.bfloat16):
    rng = np.random.RandomState(0)
    ho = (h + 2 * p - k) // s + 1
    xs_nhwc = [jnp.asarray(rng.randn(BATCH, h, h, ci), dtype)
               for _ in range(STEPS)]
    xs_nchw = [jnp.transpose(x, (0, 3, 1, 2)) for x in xs_nhwc]
    w_hwio = jnp.asarray(rng.randn(k, k, ci, co) * 0.1, dtype)
    w_oihw = jnp.transpose(w_hwio, (3, 2, 0, 1))

    def run_nchw(xs):
        acc = 0.0
        for x in xs:
            y = jax.lax.conv_general_dilated(
                x, w_oihw, (s, s), [(p, p), (p, p)],
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            y = _bn_relu(jnp.transpose(y, (0, 2, 3, 1)))
            acc = acc + y[0, 0, 0, 0].astype(jnp.float32)
        return acc

    def run_nhwc(xs):
        acc = 0.0
        for x in xs:
            y = conv_fused.conv_nhwc_xla(x, w_hwio, (s, s), (p, p))
            acc = acc + _bn_relu(y.astype(dtype))[0, 0, 0, 0].astype(
                jnp.float32)
        return acc

    def run_fused(xs):
        acc = 0.0
        for x in xs:
            y, su, ss = conv_fused.conv2d_nhwc(
                x, w_hwio, (s, s), (p, p), stats=True)
            n = y.size // co
            mean = su / n
            inv = jax.lax.rsqrt(ss / n - jnp.square(mean) + 1e-5)
            z = jnp.maximum((y.astype(jnp.float32) - mean) * inv, 0.0)
            acc = acc + z[0, 0, 0, 0]
        return acc

    out = {}
    for label, fn, xs in (("nchw", run_nchw, xs_nchw),
                          ("nhwc", run_nhwc, xs_nhwc),
                          ("fused", run_fused, xs_nhwc)):
        try:
            jfn = jax.jit(fn)
            float(np.asarray(jfn(xs)))          # compile + warm
            t0 = time.time()
            float(np.asarray(jfn(xs)))          # d2h drain = the sync
            out[label] = (time.time() - t0) / STEPS * 1e3
        except Exception as exc:  # noqa: BLE001 — survey tool
            out[label] = "FAIL:%s" % str(exc)[:40]
    return out


def _record_stage(stage, r):
    """Persist the per-stage pallas-vs-xla winner into the autotune
    cache (ISSUE 7): the fused_conv2d_bn_act lowering consults it and
    takes the identical-math XLA path where that measured faster.
    Keyed exactly as the lowering keys its lookup."""
    from paddle_tpu import tuning

    name, h, ci, co, k, s, p = stage
    fused, nhwc = r.get("fused"), r.get("nhwc")
    if not (isinstance(fused, float) and isinstance(nhwc, float)):
        return
    impl = "pallas" if fused <= nhwc else "xla"
    shape = (BATCH, h, h, ci, k, k, ci, co, s, s, p, p)
    ok = tuning.record("fused_conv2d_bn_act", shape, "bfloat16",
                       {"impl": impl}, ms=min(fused, nhwc),
                       source="conv_tune:%s" % name)
    if ok:
        print("  autotune cache <- %s impl=%s" % (name, impl))


def main():
    print("ResNet-50 stage sweep, bs=%d, %d unrolled steps, bf16" %
          (BATCH, STEPS))
    print("%-12s %10s %10s %10s  %s" % ("stage", "nchw ms", "nhwc ms",
                                        "fused ms", "fused/nchw"))
    prof = os.environ.get("CONV_TUNE_PROFILE")
    ctx = jax.profiler.trace(prof) if prof else contextlib.nullcontext()
    with ctx:
        for stage in STAGES:
            r = bench_stage(*stage)
            ratio = ""
            if isinstance(r.get("fused"), float) and \
                    isinstance(r.get("nchw"), float) and r["nchw"]:
                ratio = "%.2fx" % (r["fused"] / r["nchw"])

            def fmt(v):
                return "%10.2f" % v if isinstance(v, float) else \
                    "%10s" % v
            print("%-12s %s %s %s  %s" % (
                stage[0], fmt(r["nchw"]), fmt(r["nhwc"]),
                fmt(r["fused"]), ratio), flush=True)
            _record_stage(stage, r)


if __name__ == "__main__":
    main()
