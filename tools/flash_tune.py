"""Flash-attention kernel tuner at the secondary-bench shape.

Measures fwd+bwd wall time of the Pallas flash kernels on the real chip
at the transformer-LM bench shape (B=16, H=16, T=2048, D=64, causal) for
a grid of (block_q, block_k) and input dtypes, with the microbench traps
handled (varying inputs chained on device via lax.scan, one final d2h
drain — see .claude/skills/verify/SKILL.md).

Usage: python tools/flash_tune.py [steps]
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.kernels.flash_attention import flash_attention  # noqa: E402

B, H, T, D = 16, 16, 2048, 64
STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 12

# causal fwd+bwd analytic useful FLOPs (fwd 4*BHT^2*D, bwd 2.5x, /2 causal)
FLOPS = 0.5 * (4 + 10) * B * H * T * T * D


def bench(dtype, block_q, block_k, force_xla=False):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D), dtype)
    k = jnp.asarray(rng.randn(B, H, T, D), dtype)
    v = jnp.asarray(rng.randn(B, H, T, D), dtype)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=block_q,
                            block_k=block_k, force_xla=force_xla)
        return (o.astype(jnp.float32) ** 2).sum()

    grad = jax.grad(loss, argnums=(0, 1, 2))

    def step(carry, _):
        q, k, v = carry
        dq, dk, dv = grad(q, k, v)
        # vary the operands every iteration so nothing memoizes
        return (q + 1e-3 * dq.astype(q.dtype),
                k + 1e-3 * dk.astype(k.dtype),
                v + 1e-3 * dv.astype(v.dtype)), dq[0, 0, 0, 0]

    @jax.jit
    def run(q, k, v):
        (q, k, v), outs = jax.lax.scan(step, (q, k, v), None, length=STEPS)
        return outs.sum() + q.sum()

    r = run(q, k, v)
    float(np.asarray(r))              # warm-up + compile, full drain
    t0 = time.time()
    r = run(q, k, v)
    float(np.asarray(r))              # d2h drain is the only true sync
    dt = (time.time() - t0) / STEPS
    return dt


def main():
    print("shape B=%d H=%d T=%d D=%d causal, %d chained steps" %
          (B, H, T, D, STEPS))
    print("%-10s %6s %6s %9s %9s" % ("dtype", "bq", "bk", "ms/step",
                                     "TFLOP/s"))
    configs = []
    for dt in ("bfloat16", "float32"):
        for bq, bk in ((1024, 1024), (512, 1024), (512, 512), (256, 1024),
                       (1024, 512), (2048, 1024), (256, 512), (128, 1024)):
            configs.append((dt, bq, bk, False))
    configs.append(("bfloat16", 0, 0, True))   # XLA reference path
    for dt, bq, bk, force in configs:
        try:
            sec = bench(jnp.dtype(dt), bq, bk, force)
            print("%-10s %6d %6d %9.2f %9.1f%s" %
                  (dt, bq, bk, sec * 1e3, FLOPS / sec / 1e12,
                   "  (XLA)" if force else ""))
        except Exception as exc:  # noqa: BLE001 — tuning survey
            print("%-10s %6d %6d  FAILED: %s" % (dt, bq, bk,
                                                 str(exc)[:90]))


if __name__ == "__main__":
    main()
