"""Flash-attention kernel tuner at the secondary-bench shape.

Measures fwd+bwd wall time of the Pallas flash kernels on the real chip
at the transformer-LM bench shape (B=16, H=16, T=2048, D=64, causal) for
a grid of (block_q, block_k) and input dtypes, with the microbench traps
handled (varying inputs chained on device via lax.scan, one final d2h
drain — see .claude/skills/verify/SKILL.md).

Usage: python tools/flash_tune.py [steps]
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.kernels.flash_attention import flash_attention  # noqa: E402

B, H, T, D = 16, 8, 2048, 128   # the secondary-bench shape
STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 12

# causal fwd+bwd analytic useful FLOPs (fwd 4*BHT^2*D, bwd 2.5x, /2 causal)
FLOPS = 0.5 * (4 + 10) * B * H * T * T * D


def bench(dtype, block_q, block_k, force_xla=False,
          block_q_bwd=0, block_k_bwd=0, block_q_dkv=0, block_k_dkv=0):
    # NO lax.scan: kernels inside a while loop measured ~2x slower than
    # the identical kernels in the bench's straight-line step (see
    # PROFILE_r05.md) — unroll over distinct pre-staged inputs instead,
    # which matches how the model invokes them.
    rng = np.random.RandomState(0)
    base = [(jnp.asarray(rng.randn(B, H, T, D), dtype),
             jnp.asarray(rng.randn(B, H, T, D), dtype),
             jnp.asarray(rng.randn(B, H, T, D), dtype))
            for _ in range(STEPS)]

    bqb, bkb = (block_q_bwd or None), (block_k_bwd or None)
    bqd, bkd = (block_q_dkv or None), (block_k_dkv or None)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=block_q,
                            block_k=block_k, force_xla=force_xla,
                            block_q_bwd=bqb, block_k_bwd=bkb,
                            block_q_dkv=bqd, block_k_dkv=bkd)
        return (o.astype(jnp.float32) ** 2).sum()

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def run(ops):
        acc = 0.0
        for q, k, v in ops:      # unrolled: STEPS independent fwd+bwd
            dq, dk, dv = grad(q, k, v)
            acc = acc + dq[0, 0, 0, 0].astype(jnp.float32) + \
                dk[0, 0, 0, 0].astype(jnp.float32)
        return acc

    r = run(base)
    float(np.asarray(r))              # warm-up + compile, full drain
    t0 = time.time()
    r = run(base)
    float(np.asarray(r))              # d2h drain is the only true sync
    dt = (time.time() - t0) / STEPS
    return dt


def _record_best(best_cfg, best_sec):
    """Persist the sweep winner into the shape-keyed autotune cache
    (FLAGS_autotune_cache_dir; no-op when unset) — the kernels'
    lowerings pick it up at the next compile (ISSUE 7)."""
    from paddle_tpu import tuning

    bq, bk, bqb, bkb, bqd, bkd = best_cfg
    cfg = {"block_q": bq, "block_k": bk}
    for key, val in (("block_q_bwd", bqb), ("block_k_bwd", bkb),
                     ("block_q_dkv", bqd), ("block_k_dkv", bkd)):
        if val:
            cfg[key] = val
    ok = tuning.record("flash_attention", (B, H, T, D, T), "bfloat16",
                       cfg, ms=best_sec * 1e3, source="flash_tune")
    if ok:
        print("autotune cache <- flash_attention %s (%s)"
              % (cfg, tuning.cache_path()))
    else:
        print("autotune cache unset (FLAGS_autotune_cache_dir) — "
              "winner not persisted")


def main():
    print("shape B=%d H=%d T=%d D=%d causal, %d chained steps" %
          (B, H, T, D, STEPS))
    print("%-10s %6s %6s %9s %9s" % ("dtype", "bq", "bk", "ms/step",
                                     "TFLOP/s"))
    # (fwd_bq, fwd_bk, bwd_bq, bwd_bk, dkv_bq, dkv_bk); 0 = default —
    # bwd tiles cover dQ, the dkv pair overrides the transpose-free
    # dK/dV kernel alone (its [bk, bq] tiles stream the Q axis, so its
    # optimum can differ from dQ's; VERDICT r5 weak #2)
    configs = [
        (1024, 1024, 0, 0, 0, 0),      # current defaults (bwd capped 512)
        (1024, 1024, 512, 1024, 0, 0),
        (1024, 1024, 1024, 512, 0, 0),
        (1024, 1024, 256, 512, 0, 0),
        (1024, 1024, 512, 256, 0, 0),
        (1024, 1024, 256, 1024, 0, 0),
        (512, 1024, 0, 0, 0, 0),
        (512, 512, 0, 0, 0, 0),
        (1024, 2048, 0, 0, 0, 0),
        (1024, 2048, 512, 2048, 0, 0),
        # dkv-only sweeps at the best dq configuration
        (1024, 1024, 512, 1024, 1024, 512),
        (1024, 1024, 512, 1024, 2048, 512),
        (1024, 1024, 512, 1024, 512, 512),
        (1024, 1024, 512, 1024, 256, 1024),
        (1024, 1024, 512, 1024, 1024, 1024),
    ]
    best_cfg, best_sec = None, None
    for bq, bk, bqb, bkb, bqd, bkd in configs:
        try:
            sec = bench(jnp.bfloat16, bq, bk, False, bqb, bkb, bqd, bkd)
            print("bf16 fwd(%4d,%4d) bwd(%4s,%4s) dkv(%4s,%4s) "
                  "%9.2f ms  %7.1f TF/s" %
                  (bq, bk, bqb or "cap", bkb or "cap", bqd or "=bwd",
                   bkd or "=bwd", sec * 1e3, FLOPS / sec / 1e12))
            if best_sec is None or sec < best_sec:
                best_cfg, best_sec = (bq, bk, bqb, bkb, bqd, bkd), sec
        except Exception as exc:  # noqa: BLE001 — tuning survey
            print("bf16 fwd(%4d,%4d) bwd(%4s,%4s) dkv(%4s,%4s)  "
                  "FAILED: %s" %
                  (bq, bk, bqb or "cap", bkb or "cap", bqd or "=bwd",
                   bkd or "=bwd", str(exc)[:80]))
    if best_cfg is not None:
        _record_best(best_cfg, best_sec)


if __name__ == "__main__":
    main()
