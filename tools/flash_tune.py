"""Flash-attention kernel tuner at the secondary-bench shape.

Measures fwd+bwd wall time of the Pallas flash kernels on the real chip
at the transformer-LM bench shape (B=16, H=16, T=2048, D=64, causal) for
a grid of (block_q, block_k) and input dtypes, with the microbench traps
handled (varying inputs chained on device via lax.scan, one final d2h
drain — see .claude/skills/verify/SKILL.md).

``--ring`` sweeps the ISSUE 15 ring-attention CHUNK tiles instead: the
per-ring-step fwd+bwd pair at the longctx shard shape (one Q shard
against one K/V block, online-softmax carry threaded), recording
``ring_attention``-keyed entries the ring lowering resolves through
(kernels/flash_attention.resolve_chunk_blocks).

Usage: python tools/flash_tune.py [steps] [--ring]
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.kernels.flash_attention import flash_attention  # noqa: E402

B, H, T, D = 16, 8, 2048, 128   # the secondary-bench shape
# the longctx ring shard shape: 64k tokens over an 8-wide sp axis
RING_B, RING_H, RING_SQ, RING_D = 1, 8, 8192, 128
_args = [a for a in sys.argv[1:] if not a.startswith("-")]
RING = "--ring" in sys.argv[1:]
STEPS = int(_args[0]) if _args else 12

# causal fwd+bwd analytic useful FLOPs (fwd 4*BHT^2*D, bwd 2.5x, /2 causal)
FLOPS = 0.5 * (4 + 10) * B * H * T * T * D


def bench(dtype, block_q, block_k, force_xla=False,
          block_q_bwd=0, block_k_bwd=0, block_q_dkv=0, block_k_dkv=0):
    # NO lax.scan: kernels inside a while loop measured ~2x slower than
    # the identical kernels in the bench's straight-line step (see
    # PROFILE_r05.md) — unroll over distinct pre-staged inputs instead,
    # which matches how the model invokes them.
    rng = np.random.RandomState(0)
    base = [(jnp.asarray(rng.randn(B, H, T, D), dtype),
             jnp.asarray(rng.randn(B, H, T, D), dtype),
             jnp.asarray(rng.randn(B, H, T, D), dtype))
            for _ in range(STEPS)]

    bqb, bkb = (block_q_bwd or None), (block_k_bwd or None)
    bqd, bkd = (block_q_dkv or None), (block_k_dkv or None)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=block_q,
                            block_k=block_k, force_xla=force_xla,
                            block_q_bwd=bqb, block_k_bwd=bkb,
                            block_q_dkv=bqd, block_k_dkv=bkd)
        return (o.astype(jnp.float32) ** 2).sum()

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def run(ops):
        acc = 0.0
        for q, k, v in ops:      # unrolled: STEPS independent fwd+bwd
            dq, dk, dv = grad(q, k, v)
            acc = acc + dq[0, 0, 0, 0].astype(jnp.float32) + \
                dk[0, 0, 0, 0].astype(jnp.float32)
        return acc

    r = run(base)
    float(np.asarray(r))              # warm-up + compile, full drain
    t0 = time.time()
    r = run(base)
    float(np.asarray(r))              # d2h drain is the only true sync
    dt = (time.time() - t0) / STEPS
    return dt


def _record(kernel, shape, cfg, best_sec, source):
    """Persist a sweep winner into the shape-keyed autotune cache
    (FLAGS_autotune_cache_dir; no-op when unset) — the kernels'
    lowerings pick it up at the next compile (ISSUE 7).  The ONE
    persist-and-report path for every sweep in this tool."""
    from paddle_tpu import tuning

    ok = tuning.record(kernel, shape, "bfloat16", cfg,
                       ms=best_sec * 1e3, source=source)
    if ok:
        print("autotune cache <- %s %s (%s)"
              % (kernel, cfg, tuning.cache_path()))
    else:
        print("autotune cache unset (FLAGS_autotune_cache_dir) — "
              "winner not persisted")


def _record_best(best_cfg, best_sec):
    bq, bk, bqb, bkb, bqd, bkd = best_cfg
    cfg = {"block_q": bq, "block_k": bk}
    for key, val in (("block_q_bwd", bqb), ("block_k_bwd", bkb),
                     ("block_q_dkv", bqd), ("block_k_dkv", bkd)):
        if val:
            cfg[key] = val
    _record("flash_attention", (B, H, T, D, T), cfg, best_sec,
            "flash_tune")


def bench_ring_chunk(dtype, block_q, block_k, steps):
    """fwd+bwd wall of ONE ring chunk update (the per-ring-step inner
    compute): fold a K/V block into the carry, finalize, backprop
    through the chunk pair — the unit the ring loop repeats p times."""
    from paddle_tpu.kernels.flash_attention import (
        NEG_INF, chunk_finalize, flash_attention_chunk,
        flash_attention_chunk_bwd)

    rng = np.random.RandomState(0)
    base = [tuple(jnp.asarray(rng.randn(RING_B, RING_H, RING_SQ, RING_D),
                              dtype) for _ in range(3))
            for _ in range(steps)]

    def one(q, k, v):
        m = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
        l = jnp.zeros(q.shape[:3], jnp.float32)
        acc = jnp.zeros(q.shape, jnp.float32)
        m, l, acc = flash_attention_chunk(
            q, k, v, m, l, acc, causal=True, block_q=block_q,
            block_k=block_k)
        out, lse = chunk_finalize(m, l, acc, q.dtype)
        do = out  # any cotangent of the right shape/dtype
        delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
        dq, dk, dv = flash_attention_chunk_bwd(
            q, k, v, do, lse, delta, causal=True, block_q=block_q,
            block_k=block_k)
        return dq[0, 0, 0, 0].astype(jnp.float32) + \
            dk[0, 0, 0, 0].astype(jnp.float32)

    @jax.jit
    def run(ops):
        acc = 0.0
        for q, k, v in ops:      # unrolled, like bench()
            acc = acc + one(q, k, v)
        return acc

    float(np.asarray(run(base)))      # warm-up + compile
    t0 = time.time()
    float(np.asarray(run(base)))
    return (time.time() - t0) / steps


def main_ring():
    print("ring chunk shape B=%d H=%d Sq=Sk=%d D=%d causal diag, "
          "%d chained steps" % (RING_B, RING_H, RING_SQ, RING_D, STEPS))
    # causal diag fwd+bwd useful FLOPs of one chunk (/2 causal diag)
    flops = 0.5 * (4 + 10) * RING_B * RING_H * RING_SQ * RING_SQ * RING_D
    configs = [(1024, 1024), (512, 1024), (1024, 512), (512, 512),
               (2048, 1024), (1024, 2048), (256, 1024), (2048, 2048)]
    best_cfg, best_sec = None, None
    for bq, bk in configs:
        try:
            sec = bench_ring_chunk(jnp.bfloat16, bq, bk, STEPS)
            print("bf16 (%4d,%4d)  %9.2f ms  %7.1f TF/s"
                  % (bq, bk, sec * 1e3, flops / sec / 1e12))
            if best_sec is None or sec < best_sec:
                best_cfg, best_sec = (bq, bk), sec
        except Exception as exc:  # noqa: BLE001 — tuning survey
            print("bf16 (%4d,%4d)  FAILED: %s" % (bq, bk,
                                                  str(exc)[:80]))
    if best_cfg is None:
        return
    _record("ring_attention",
            (RING_B, RING_H, RING_SQ, RING_D, RING_SQ),
            {"block_q": best_cfg[0], "block_k": best_cfg[1]},
            best_sec, "flash_tune --ring")


def main():
    if RING:
        return main_ring()
    print("shape B=%d H=%d T=%d D=%d causal, %d chained steps" %
          (B, H, T, D, STEPS))
    print("%-10s %6s %6s %9s %9s" % ("dtype", "bq", "bk", "ms/step",
                                     "TFLOP/s"))
    # (fwd_bq, fwd_bk, bwd_bq, bwd_bk, dkv_bq, dkv_bk); 0 = default —
    # bwd tiles cover dQ, the dkv pair overrides the transpose-free
    # dK/dV kernel alone (its [bk, bq] tiles stream the Q axis, so its
    # optimum can differ from dQ's; VERDICT r5 weak #2)
    configs = [
        (1024, 1024, 0, 0, 0, 0),      # current defaults (bwd capped 512)
        (1024, 1024, 512, 1024, 0, 0),
        (1024, 1024, 1024, 512, 0, 0),
        (1024, 1024, 256, 512, 0, 0),
        (1024, 1024, 512, 256, 0, 0),
        (1024, 1024, 256, 1024, 0, 0),
        (512, 1024, 0, 0, 0, 0),
        (512, 512, 0, 0, 0, 0),
        (1024, 2048, 0, 0, 0, 0),
        (1024, 2048, 512, 2048, 0, 0),
        # dkv-only sweeps at the best dq configuration
        (1024, 1024, 512, 1024, 1024, 512),
        (1024, 1024, 512, 1024, 2048, 512),
        (1024, 1024, 512, 1024, 512, 512),
        (1024, 1024, 512, 1024, 256, 1024),
        (1024, 1024, 512, 1024, 1024, 1024),
    ]
    best_cfg, best_sec = None, None
    for bq, bk, bqb, bkb, bqd, bkd in configs:
        try:
            sec = bench(jnp.bfloat16, bq, bk, False, bqb, bkb, bqd, bkd)
            print("bf16 fwd(%4d,%4d) bwd(%4s,%4s) dkv(%4s,%4s) "
                  "%9.2f ms  %7.1f TF/s" %
                  (bq, bk, bqb or "cap", bkb or "cap", bqd or "=bwd",
                   bkd or "=bwd", sec * 1e3, FLOPS / sec / 1e12))
            if best_sec is None or sec < best_sec:
                best_cfg, best_sec = (bq, bk, bqb, bkb, bqd, bkd), sec
        except Exception as exc:  # noqa: BLE001 — tuning survey
            print("bf16 fwd(%4d,%4d) bwd(%4s,%4s) dkv(%4s,%4s)  "
                  "FAILED: %s" %
                  (bq, bk, bqb or "cap", bkb or "cap", bqd or "=bwd",
                   bkd or "=bwd", str(exc)[:80]))
    if best_cfg is not None:
        _record_best(best_cfg, best_sec)


if __name__ == "__main__":
    main()
