#!/usr/bin/env python
"""Serving-tier load harness (ISSUE 9): open-loop Poisson arrivals
against the continuous-batching InferenceServer, with a single-request-
at-a-time floor to quantify the batching win, and a hot model swap
under load asserting zero dropped requests.

Phases (all on the CPU tier unless JAX_PLATFORMS says otherwise):
  floor      closed-loop serial predict() through a max_batch=1,
             max_wait=0 server — what one request at a time sustains.
             This is the Clipper no-batching baseline.
  saturated  bounded-window pipelined submits (the capacity probe):
             the max QPS the batcher reaches when arrivals never gate.
  poisson    open-loop Poisson arrivals at ``--rate-x`` times the floor
             QPS (open-loop = every arrival is an independent simulated
             client; completions are recorded via future callbacks so a
             slow server cannot gate the arrival process).  Halfway
             through, ``swap()`` flips the tenant to a second model
             version built from different parameters — every request
             must complete and classify bit-clean as served by exactly
             one version (zero dropped, zero torn).

Output: ONE JSON line (``--out FILE`` also writes it to a file —
SERVE_BENCH.json in the repo ledger), including the batch-occupancy
histogram and the queue-wait/assemble/dispatch phase breakdown from
the always-on metrics registry, plus the aot_load_fallback_total
counter (a fleet quietly re-jitting is visible here, not only in
stderr).  ``--quick`` shrinks everything to a seconds-long tier-1
smoke (wired like pserver_bench --quick).  Set FLAGS_telemetry=1 and
FLAGS_telemetry_dump_dir to get the serve.batch/assemble/dispatch
spans into tools/trace_report.py.
"""
import argparse
import json
import os
import random
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np

# model dims (env-overridable like pserver_bench): heavy enough that
# the single-request floor pays real per-dispatch compute — the
# batching win being measured is amortization of exactly that
D_IN = int(os.environ.get("SVB_D_IN", "128"))
HIDDEN = int(os.environ.get("SVB_HIDDEN", "512"))
D_OUT = int(os.environ.get("SVB_D_OUT", "32"))


def _build_and_save(dirname, seed, max_batch):
    """Save one model version; ``seed`` differentiates the parameter
    draw so the swap phase can classify which engine served each
    request (constant inits would be degenerate: softmax over equal
    logits answers uniform for every version)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope

    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    init = fluid.initializer.UniformInitializer
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[D_IN],
                                      dtype="float32")
                h = fluid.layers.fc(
                    x, size=HIDDEN, act="tanh",
                    param_attr=fluid.ParamAttr(
                        initializer=init(-0.08, 0.08, seed=seed)))
                h = fluid.layers.fc(
                    h, size=HIDDEN, act="tanh",
                    param_attr=fluid.ParamAttr(
                        initializer=init(-0.08, 0.08, seed=seed + 1)))
                out = fluid.layers.fc(
                    h, size=D_OUT, act="softmax",
                    param_attr=fluid.ParamAttr(
                        initializer=init(-0.08, 0.08, seed=seed + 2)))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(
            dirname, ["x"], [out], exe, main_program=main,
            aot_feed_specs={"x": ((1, D_IN), "float32")})


def _pctl(vals, p):
    from paddle_tpu.observability.metrics import nearest_rank

    return nearest_rank(sorted(vals), p)


def _lat_ms(vals):
    return {"p50_ms": round(_pctl(vals, 50) * 1e3, 3),
            "p90_ms": round(_pctl(vals, 90) * 1e3, 3),
            "p99_ms": round(_pctl(vals, 99) * 1e3, 3)}


def _measure_floor(model_dir, x, seconds):
    """Single-request-at-a-time QPS: serial closed loop, no batching
    (max_batch=1), no coalesce wait (max_wait=0)."""
    from paddle_tpu.serving import InferenceServer

    lats = []
    with InferenceServer(max_batch=1, max_wait_us=0) as srv:
        srv.load("m", model_dir)
        for _ in range(10):
            srv.predict("m", {"x": x})
        t_end = time.perf_counter() + seconds
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() < t_end:
            t = time.perf_counter()
            srv.predict("m", {"x": x})
            lats.append(time.perf_counter() - t)
            n += 1
        wall = time.perf_counter() - t0
    return dict(qps=round(n / wall, 1), n=n, **_lat_ms(lats))


def _measure_saturated(srv, x, seconds, window):
    """Capacity probe: keep ``window`` requests in flight."""
    from collections import deque

    done = []
    lock = threading.Lock()

    def _done_cb(t0):
        def cb(fut):
            fut.result()
            with lock:
                done.append(time.perf_counter() - t0)
        return cb

    for _ in range(5):
        srv.predict("m", {"x": x})
    inflight = deque()
    t0 = time.perf_counter()
    t_end = t0 + seconds
    n = 0
    while time.perf_counter() < t_end:
        while len(inflight) >= window:
            inflight.popleft().result()
        t = time.perf_counter()
        fut = srv.submit("m", {"x": x})
        fut.add_done_callback(_done_cb(t))
        inflight.append(fut)
        n += 1
    for f in inflight:
        f.result(60)
    wall = time.perf_counter() - t0
    with lock:
        lats = list(done)
    return dict(qps=round(n / wall, 1), n=n, window=window,
                **_lat_ms(lats))


def _poisson(srv, x, ref_v1, seconds, rate, seed=7, swap_to=None,
             swap_at=0.5):
    """Open-loop arrivals at ``rate``/s; with ``swap_to`` set, swap the
    tenant to that model dir at ``swap_at`` x seconds.  Returns stats +
    the zero-dropped/zero-torn classification."""
    rng = random.Random(seed)
    results = []     # (latency_s, output ndarray) via callbacks
    lock = threading.Lock()
    errors = []

    def _cb(t0):
        def cb(fut):
            t = time.perf_counter() - t0
            try:
                out = next(iter(fut.result().values()))
            except Exception as e:       # a dropped request
                with lock:
                    errors.append(repr(e))
                return
            with lock:
                results.append((t, np.asarray(out)))
        return cb

    swap_state = {}

    def _swapper():
        time.sleep(seconds * swap_at)
        t0 = time.perf_counter()
        srv.swap("m", swap_to)
        swap_state["swap_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)

    swapper = None
    if swap_to is not None:
        swapper = threading.Thread(target=_swapper, daemon=True)
        swapper.start()
    n = 0
    t0 = time.perf_counter()
    next_t = t0
    t_end = t0 + seconds
    while next_t < t_end:
        # sleep, never spin: a spinning arrival thread starves the
        # dispatcher of the GIL and manufactures an overload that is
        # the harness's, not the server's.  Oversleep just lowers the
        # realized rate — reported from the actual submission count.
        gap = next_t - time.perf_counter()
        if gap > 0:
            time.sleep(gap)
        t = time.perf_counter()
        fut = srv.submit("m", {"x": x})
        fut.add_done_callback(_cb(t))
        n += 1
        next_t += rng.expovariate(rate)
    if swapper is not None:
        swapper.join(timeout=120)
    # drain: every submitted request must complete
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        with lock:
            if len(results) + len(errors) >= n:
                break
        time.sleep(0.01)
    wall = time.perf_counter() - t0
    ref_v2 = np.asarray(next(iter(
        srv.predict("m", {"x": x}).values())))
    with lock:
        lats = [r[0] for r in results]
        v1 = sum(1 for _, o in results
                 if np.allclose(o, ref_v1, atol=1e-5))
        v2 = 0 if swap_to is None else sum(
            1 for _, o in results
            if np.allclose(o, ref_v2, atol=1e-5))
        completed = len(results)
        n_err = len(errors)
    torn = completed - v1 - v2
    stats = dict(
        offered_qps=round(rate, 1), qps=round(completed / wall, 1),
        n_requests=n, n_simulated_clients=n, completed=completed,
        duration_s=round(wall, 2), **_lat_ms(lats))
    if swap_to is None:
        return stats, dict(zero_dropped=(completed == n and not n_err),
                           dropped=n - completed, errors=errors[:5])
    return stats, dict(
        zero_dropped=(completed == n and n_err == 0),
        dropped=n - completed, errors=errors[:5],
        served_v1=v1, served_v2=v2, torn=torn,
        swap_ms=swap_state.get("swap_ms"))


def _wire_sanity(srv, x):
    """One request over the socket endpoint — the fastwire-framed
    Predict method answers and matches the in-process result."""
    from paddle_tpu.serving import PredictClient

    port = srv.start_endpoint()
    with PredictClient("127.0.0.1", port) as cli:
        t0 = time.perf_counter()
        outs = cli.predict("m", {"x": x})
        lat = time.perf_counter() - t0
    ref = srv.predict("m", {"x": x})
    ok = all(np.allclose(outs[k], ref[k], atol=1e-5) for k in outs)
    return {"ok": bool(ok), "latency_ms": round(lat * 1e3, 3),
            "port": port}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="seconds-long tier-1 smoke (CPU)")
    ap.add_argument("--out", default="",
                    help="also write the JSON to this file")
    ap.add_argument("--rate-x", type=float, default=4.0,
                    help="poisson offered rate as a multiple of the "
                         "measured floor QPS")
    ap.add_argument("--seconds", type=float, default=0.0,
                    help="override per-phase duration")
    args = ap.parse_args(argv)

    import tempfile

    from paddle_tpu.core.flags import FLAGS, apply_xla_flags
    from paddle_tpu.inference import aot as aot_mod
    from paddle_tpu.observability import metrics
    from paddle_tpu.serving import InferenceServer

    apply_xla_flags()
    seconds = args.seconds or (1.0 if args.quick else 6.0)
    max_batch = int(os.environ.get("SVB_MAX_BATCH",
                                   "8" if args.quick else "16"))
    max_wait_us = int(os.environ.get("SVB_MAX_WAIT_US", "2000"))
    tmp = tempfile.mkdtemp(prefix="serve_bench_")
    d1, d2 = os.path.join(tmp, "v1"), os.path.join(tmp, "v2")
    t_build = time.perf_counter()
    _build_and_save(d1, 11, max_batch)
    _build_and_save(d2, 911, max_batch)
    build_s = time.perf_counter() - t_build
    x = np.linspace(-1, 1, D_IN).astype(np.float32).reshape(1, D_IN)

    floor = _measure_floor(d1, x, seconds)

    metrics.zero_all()
    srv = InferenceServer(max_batch=max_batch, max_wait_us=max_wait_us)
    t_load = time.perf_counter()
    srv.load("m", d1)
    load_s = time.perf_counter() - t_load
    ref_v1 = np.asarray(next(iter(srv.predict("m", {"x": x}).values())))

    saturated = _measure_saturated(srv, x, seconds,
                                   window=4 * max_batch)
    metrics.zero_all()
    # open-loop offered rate: rate_x x floor, capped under the probed
    # capacity — an open-loop rate above capacity has no steady state
    # (the queue and p99 grow without bound for as long as you let it)
    rate = min(args.rate_x * floor["qps"], 0.65 * saturated["qps"])
    # headline phase: steady open-loop load, no configuration churn
    poisson, steady_drop = _poisson(srv, x, ref_v1, 2 * seconds, rate)
    # swap phase: same load while swap() builds + flips to v2 — the
    # shadow compile competes for the host, so its latency spike is
    # reported HERE, not folded into the steady-state headline
    poisson_swap, swap = _poisson(srv, x, ref_v1, 2 * seconds, rate,
                                  seed=13, swap_to=d2, swap_at=0.33)
    swap["steady_phase_dropped"] = steady_drop["dropped"]
    snap = metrics.snapshot()
    occupancy = snap["serve_batch_occupancy"]
    phases = {k: {"p50_ms": snap[k]["p50"], "p99_ms": snap[k]["p99"],
                  "count": snap[k]["count"]}
              for k in ("serve_queue_wait_ms", "serve_batch_assemble_ms",
                        "serve_dispatch_ms")}
    wire = _wire_sanity(srv, x)
    srv.close()

    speedup = round(poisson["qps"] / max(floor["qps"], 1e-9), 2)
    speedup_saturated = round(
        saturated["qps"] / max(floor["qps"], 1e-9), 2)
    p99_budget_ms = max(2.0 * floor["p99_ms"], 10.0)
    out = {
        "metric": "serve_bench",
        "quick": bool(args.quick),
        "platform": os.environ.get("JAX_PLATFORMS", ""),
        "model": {"d_in": D_IN, "hidden": HIDDEN, "d_out": D_OUT},
        "max_batch": max_batch,
        "max_wait_us": max_wait_us,
        "build_s": round(build_s, 2),
        "load_warm_s": round(load_s, 2),
        "floor": floor,
        "saturated": saturated,
        "poisson": poisson,
        "poisson_under_swap": poisson_swap,
        "speedup_vs_floor": speedup,
        "speedup_saturated_vs_floor": speedup_saturated,
        "p99_budget_ms": round(p99_budget_ms, 3),
        "within_p99_budget": poisson["p99_ms"] <= p99_budget_ms,
        "batch_occupancy": {"count": occupancy["count"],
                            "p50": occupancy["p50"],
                            "buckets": occupancy["buckets"]},
        "phases": phases,
        "swap": swap,
        "wire": wire,
        "aot_load_fallback_total":
            metrics.counter("aot_load_fallback_total").value,
        "aot_load_fallbacks": list(aot_mod.FALLBACKS),
        "ok": bool(speedup >= 3.0
                   and poisson["p99_ms"] <= p99_budget_ms
                   and steady_drop["zero_dropped"]
                   and swap["zero_dropped"] and swap["torn"] == 0
                   and wire["ok"]),
    }
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
