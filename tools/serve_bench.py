#!/usr/bin/env python
"""Serving-tier load harness (ISSUE 9): open-loop Poisson arrivals
against the continuous-batching InferenceServer, with a single-request-
at-a-time floor to quantify the batching win, and a hot model swap
under load asserting zero dropped requests.

Phases (all on the CPU tier unless JAX_PLATFORMS says otherwise):
  floor      closed-loop serial predict() through a max_batch=1,
             max_wait=0 server — what one request at a time sustains.
             This is the Clipper no-batching baseline.
  saturated  bounded-window pipelined submits (the capacity probe):
             the max QPS the batcher reaches when arrivals never gate.
  poisson    open-loop Poisson arrivals at ``--rate-x`` times the floor
             QPS (open-loop = every arrival is an independent simulated
             client; completions are recorded via future callbacks so a
             slow server cannot gate the arrival process).  Halfway
             through, ``swap()`` flips the tenant to a second model
             version built from different parameters — every request
             must complete and classify bit-clean as served by exactly
             one version (zero dropped, zero torn).

Output: ONE JSON line (``--out FILE`` also writes it to a file —
SERVE_BENCH.json in the repo ledger), including the batch-occupancy
histogram and the queue-wait/assemble/dispatch phase breakdown from
the always-on metrics registry, plus the aot_load_fallback_total
counter (a fleet quietly re-jitting is visible here, not only in
stderr).  ``--quick`` shrinks everything to a seconds-long tier-1
smoke (wired like pserver_bench --quick).  Set FLAGS_telemetry=1 and
FLAGS_telemetry_dump_dir to get the serve.batch/assemble/dispatch
spans into tools/trace_report.py.
"""
import argparse
import json
import os
import random
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np

# model dims (env-overridable like pserver_bench): heavy enough that
# the single-request floor pays real per-dispatch compute — the
# batching win being measured is amortization of exactly that
D_IN = int(os.environ.get("SVB_D_IN", "128"))
HIDDEN = int(os.environ.get("SVB_HIDDEN", "512"))
D_OUT = int(os.environ.get("SVB_D_OUT", "32"))


def _build_and_save(dirname, seed, max_batch):
    """Save one model version; ``seed`` differentiates the parameter
    draw so the swap phase can classify which engine served each
    request (constant inits would be degenerate: softmax over equal
    logits answers uniform for every version)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope

    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    init = fluid.initializer.UniformInitializer
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[D_IN],
                                      dtype="float32")
                h = fluid.layers.fc(
                    x, size=HIDDEN, act="tanh",
                    param_attr=fluid.ParamAttr(
                        initializer=init(-0.08, 0.08, seed=seed)))
                h = fluid.layers.fc(
                    h, size=HIDDEN, act="tanh",
                    param_attr=fluid.ParamAttr(
                        initializer=init(-0.08, 0.08, seed=seed + 1)))
                out = fluid.layers.fc(
                    h, size=D_OUT, act="softmax",
                    param_attr=fluid.ParamAttr(
                        initializer=init(-0.08, 0.08, seed=seed + 2)))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(
            dirname, ["x"], [out], exe, main_program=main,
            aot_feed_specs={"x": ((1, D_IN), "float32")})


def _pctl(vals, p):
    from paddle_tpu.observability.metrics import nearest_rank

    return nearest_rank(sorted(vals), p)


def _lat_ms(vals):
    return {"p50_ms": round(_pctl(vals, 50) * 1e3, 3),
            "p90_ms": round(_pctl(vals, 90) * 1e3, 3),
            "p99_ms": round(_pctl(vals, 99) * 1e3, 3)}


def _measure_floor(model_dir, x, seconds):
    """Single-request-at-a-time QPS: serial closed loop, no batching
    (max_batch=1), no coalesce wait (max_wait=0)."""
    from paddle_tpu.serving import InferenceServer

    lats = []
    with InferenceServer(max_batch=1, max_wait_us=0) as srv:
        srv.load("m", model_dir)
        for _ in range(10):
            srv.predict("m", {"x": x})
        t_end = time.perf_counter() + seconds
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() < t_end:
            t = time.perf_counter()
            srv.predict("m", {"x": x})
            lats.append(time.perf_counter() - t)
            n += 1
        wall = time.perf_counter() - t0
    return dict(qps=round(n / wall, 1), n=n, **_lat_ms(lats))


def _measure_saturated(srv, x, seconds, window):
    """Capacity probe: keep ``window`` requests in flight."""
    from collections import deque

    done = []
    lock = threading.Lock()

    def _done_cb(t0):
        def cb(fut):
            fut.result()
            with lock:
                done.append(time.perf_counter() - t0)
        return cb

    for _ in range(5):
        srv.predict("m", {"x": x})
    inflight = deque()
    t0 = time.perf_counter()
    t_end = t0 + seconds
    n = 0
    while time.perf_counter() < t_end:
        while len(inflight) >= window:
            inflight.popleft().result()
        t = time.perf_counter()
        fut = srv.submit("m", {"x": x})
        fut.add_done_callback(_done_cb(t))
        inflight.append(fut)
        n += 1
    for f in inflight:
        f.result(60)
    wall = time.perf_counter() - t0
    with lock:
        lats = list(done)
    return dict(qps=round(n / wall, 1), n=n, window=window,
                **_lat_ms(lats))


def _poisson(srv, x, ref_v1, seconds, rate, seed=7, swap_to=None,
             swap_at=0.5):
    """Open-loop arrivals at ``rate``/s; with ``swap_to`` set, swap the
    tenant to that model dir at ``swap_at`` x seconds.  Returns stats +
    the zero-dropped/zero-torn classification."""
    rng = random.Random(seed)
    results = []     # (latency_s, output ndarray) via callbacks
    lock = threading.Lock()
    errors = []

    def _cb(t0):
        def cb(fut):
            t = time.perf_counter() - t0
            try:
                out = next(iter(fut.result().values()))
            except Exception as e:       # a dropped request
                with lock:
                    errors.append(repr(e))
                return
            with lock:
                results.append((t, np.asarray(out)))
        return cb

    swap_state = {}

    def _swapper():
        time.sleep(seconds * swap_at)
        t0 = time.perf_counter()
        srv.swap("m", swap_to)
        swap_state["swap_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)

    swapper = None
    if swap_to is not None:
        swapper = threading.Thread(target=_swapper, daemon=True)
        swapper.start()
    n = 0
    t0 = time.perf_counter()
    next_t = t0
    t_end = t0 + seconds
    while next_t < t_end:
        # sleep, never spin: a spinning arrival thread starves the
        # dispatcher of the GIL and manufactures an overload that is
        # the harness's, not the server's.  Oversleep just lowers the
        # realized rate — reported from the actual submission count.
        gap = next_t - time.perf_counter()
        if gap > 0:
            time.sleep(gap)
        t = time.perf_counter()
        fut = srv.submit("m", {"x": x})
        fut.add_done_callback(_cb(t))
        n += 1
        next_t += rng.expovariate(rate)
    if swapper is not None:
        swapper.join(timeout=120)
    # drain: every submitted request must complete
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        with lock:
            if len(results) + len(errors) >= n:
                break
        time.sleep(0.01)
    wall = time.perf_counter() - t0
    ref_v2 = np.asarray(next(iter(
        srv.predict("m", {"x": x}).values())))
    with lock:
        lats = [r[0] for r in results]
        v1 = sum(1 for _, o in results
                 if np.allclose(o, ref_v1, atol=1e-5))
        v2 = 0 if swap_to is None else sum(
            1 for _, o in results
            if np.allclose(o, ref_v2, atol=1e-5))
        completed = len(results)
        n_err = len(errors)
    torn = completed - v1 - v2
    stats = dict(
        offered_qps=round(rate, 1), qps=round(completed / wall, 1),
        n_requests=n, n_simulated_clients=n, completed=completed,
        duration_s=round(wall, 2), **_lat_ms(lats))
    if swap_to is None:
        return stats, dict(zero_dropped=(completed == n and not n_err),
                           dropped=n - completed, errors=errors[:5])
    return stats, dict(
        zero_dropped=(completed == n and n_err == 0),
        dropped=n - completed, errors=errors[:5],
        served_v1=v1, served_v2=v2, torn=torn,
        swap_ms=swap_state.get("swap_ms"))


# ---------------------------------------------------------------------------
# Generate mode (ISSUE 11): token-level decode under Poisson arrivals
# ---------------------------------------------------------------------------

# bench LM (env-overridable): dims sized so int8 weight quantization
# holds greedy-token parity with a measured margin certificate (min
# top-2 logit margin > max |logit delta| at every one of >= 64 steps —
# scanned over seeds; SVB_GEN_SEED=3 is the certified draw)
GEN_VOCAB = int(os.environ.get("SVB_GEN_VOCAB", "64"))
GEN_DMODEL = int(os.environ.get("SVB_GEN_DMODEL", "128"))
GEN_HEADS = int(os.environ.get("SVB_GEN_HEADS", "4"))
GEN_LAYERS = int(os.environ.get("SVB_GEN_LAYERS", "3"))
GEN_DFF = int(os.environ.get("SVB_GEN_DFF", "256"))
GEN_SEED = int(os.environ.get("SVB_GEN_SEED", "3"))
GEN_BLOCK = int(os.environ.get("SVB_GEN_BLOCK", "16"))
GEN_MAX_BLOCKS = int(os.environ.get("SVB_GEN_MAX_BLOCKS", "8"))


def _gen_cfg(max_batch, kv_blocks):
    from paddle_tpu.serving import tiny_lm

    cfg, params = tiny_lm(GEN_SEED, vocab=GEN_VOCAB, d_model=GEN_DMODEL,
                          n_heads=GEN_HEADS, n_layers=GEN_LAYERS,
                          d_ff=GEN_DFF, block_size=GEN_BLOCK,
                          max_blocks=GEN_MAX_BLOCKS,
                          max_batch=max_batch)
    return cfg, params, int(kv_blocks)


def _gen_prompts(rng, n, lo=4, hi=24):
    return [rng.randint(0, GEN_VOCAB, size=rng.randint(lo, hi))
            .tolist() for _ in range(n)]


def _gen_floor(srv, prompt, max_new):
    """Single-sequence closed loop: solo decode rate — the no-batching
    baseline the continuous decode batch amortizes against.  One
    unmeasured warm-up generation first: a cold engine's first solo
    pass kicks the narrow (1, nb) decode-bucket background compiles,
    and those would contend with the measured loop for host CPU."""
    srv.generate("g", prompt, max_new_tokens=max_new).result(300)
    time.sleep(0.3)      # let stragglers of the bucket compiles land
    t0 = time.perf_counter()
    res = srv.generate("g", prompt, max_new_tokens=max_new).result(300)
    wall = time.perf_counter() - t0
    itl = sorted(res["itl_ms"])
    return {"tokens": len(res["tokens"]),
            "tokens_s": round(len(res["tokens"]) / wall, 1),
            "ttft_ms": round(res["ttft_ms"], 3),
            "itl_p50_ms": round(_pctl(itl, 50), 3),
            "itl_p99_ms": round(_pctl(itl, 99), 3)}


def _gen_capacity(srv, prompts, max_new):
    """Full-batch token throughput: submit a closed wave and measure
    tokens/s — calibrates the Poisson offered rate."""
    t0 = time.perf_counter()
    futs = [srv.generate("g", p, max_new_tokens=max_new)
            for p in prompts]
    toks = sum(len(f.result(600)["tokens"]) for f in futs)
    wall = time.perf_counter() - t0
    return toks / wall


def _gen_poisson(srv, prompts, max_new, seconds, rate_rps, seed=17):
    """Open-loop Poisson generate arrivals at ``rate_rps``; returns
    (stats, per-request results).  Same sleep-don't-spin arrival
    process as the predict phases; completions via future callbacks."""
    rng = random.Random(seed)
    results, errors = [], []
    lock = threading.Lock()

    def _cb(fut):
        try:
            r = fut.result()
        except Exception as e:
            with lock:
                errors.append(repr(e))
            return
        with lock:
            results.append(r)

    n = 0
    t0 = time.perf_counter()
    next_t = t0
    t_end = t0 + seconds
    while next_t < t_end:
        gap = next_t - time.perf_counter()
        if gap > 0:
            time.sleep(gap)
        fut = srv.generate("g", prompts[n % len(prompts)],
                           max_new_tokens=max_new)
        fut.add_done_callback(_cb)
        n += 1
        next_t += rng.expovariate(rate_rps)
    deadline = time.perf_counter() + 300
    while time.perf_counter() < deadline:
        with lock:
            if len(results) + len(errors) >= n:
                break
        time.sleep(0.01)
    wall = time.perf_counter() - t0
    with lock:
        done = list(results)
        errs = list(errors)
    toks = sum(len(r["tokens"]) for r in done)
    ttfts = sorted(r["ttft_ms"] for r in done)
    itls = sorted(v for r in done for v in r["itl_ms"])
    stats = {
        "offered_rps": round(rate_rps, 2),
        "n_requests": n, "completed": len(done),
        "duration_s": round(wall, 2),
        "tokens": toks,
        "tokens_s": round(toks / wall, 1),
        "ttft_p50_ms": round(_pctl(ttfts, 50), 3),
        "ttft_p99_ms": round(_pctl(ttfts, 99), 3),
        "itl_p50_ms": round(_pctl(itls, 50), 3),
        "itl_p99_ms": round(_pctl(itls, 99), 3),
        "preempted_requests": sum(1 for r in done if r["preempted"]),
    }
    return stats, {"zero_dropped": len(done) == n and not errs,
                   "dropped": n - len(done), "errors": errs[:5]}


def _gen_int8_parity(max_batch, kv_blocks, steps):
    """Greedy-token parity fp32 vs int8-quantized decode, closed loop
    over ``steps`` tokens, with the logit-margin certificate: at every
    step of the (matched) trajectory the fp32 top-2 margin must exceed
    the worst fp32-vs-int8 logit delta — token parity then holds with
    measured headroom, not by luck."""
    from concurrent.futures import Future

    from paddle_tpu.serving.batcher import TokenScheduler
    from paddle_tpu.serving.generative import (GenRequest,
                                               GenerativeEngine)

    cfg, params, kv = _gen_cfg(max_batch, kv_blocks)
    prompt = np.random.RandomState(1000 + GEN_SEED) \
        .randint(0, GEN_VOCAB, size=12).tolist()

    def run(quant):
        eng = GenerativeEngine(cfg, params, quant=quant, kv_blocks=kv,
                               name="parity-" + (quant or "fp32"),
                               warm=False)
        req = GenRequest(prompt, steps, None, Future())
        try:
            req.blocks = eng.pool.alloc(
                eng.pool.blocks_for(len(prompt)))
            out = [eng.prefill(req)]
            req.out = out
            sched = TokenScheduler(eng.pool, cfg.max_batch)
            logits = []
            while len(out) < steps:
                cap = len(req.blocks) * cfg.block_size
                if req.context_len >= cap:
                    sched.grow(req)
                t, lg = eng.decode([req], with_logits=True)
                logits.append(lg[0])
                out.append(int(t[0]))
            return out, logits
        finally:
            eng.free_sequence(req)
            eng.close()

    tf, lf = run("")
    tq, lq = run("int8")
    n_match = sum(a == b for a, b in zip(tf, tq))
    deltas = [float(np.abs(a - b).max()) for a, b in zip(lf, lq)]
    margins = []
    for a in lf:
        srt = np.sort(a)[::-1]
        margins.append(float(srt[0] - srt[1]))
    parity_ok = n_match == steps
    return {
        "steps": steps,
        "token_parity": "%d/%d" % (n_match, steps),
        "parity_ok": parity_ok,
        # the logit certificate covers the DECODE steps (steps - 1):
        # the first token comes from the prefill dispatch, which is
        # token-compared above but exposes no logits
        "certified_decode_steps": len(deltas),
        "max_logit_delta": round(max(deltas), 5) if deltas else 0.0,
        "min_top2_margin": round(min(margins), 5) if margins else 0.0,
        "certified": bool(parity_ok and deltas
                          and min(margins) > max(deltas)),
        "quantized": "wqkv/wo/w1/w2 int8 per-chunk symmetric "
                     "(compress.quantize_symmetric); embed/pos/"
                     "lm_head/LN fp32",
    }


# ---------------------------------------------------------------------------
# Prefix-cache + speculative phases (ISSUE 19)
# ---------------------------------------------------------------------------

# speculative bench LM (env-overridable): 3 layers whose layer 0 is
# dimension-shared with the 1-layer draft; the two DEEP layers carry a
# fat (SVB_SPEC_FAT-wide) MLP whose outputs are damped by
# SVB_SPEC_DAMP, so the draft predicts the target's greedy argmax at
# ~0.95+ acceptance while the target pays ~6x the draft's FLOPs — the
# regime speculative decoding exists for (cheap proposer, expensive
# verifier), scaled to a CI-sized model.  SVB_SPEC_DAMP=0.002 is the
# certified draw: smaller perturbations leave the argmax unmoved on
# most steps without making the deep layers a no-op
SPEC_VOCAB = int(os.environ.get("SVB_SPEC_VOCAB", "128"))
SPEC_DMODEL = int(os.environ.get("SVB_SPEC_DMODEL", "256"))
SPEC_HEADS = int(os.environ.get("SVB_SPEC_HEADS", "4"))
SPEC_FAT = int(os.environ.get("SVB_SPEC_FAT", "8192"))
SPEC_DAMP = float(os.environ.get("SVB_SPEC_DAMP", "0.002"))
SPEC_K = int(os.environ.get("SVB_SPEC_K", "8"))
SPEC_SEED = int(os.environ.get("SVB_SPEC_SEED", "3"))
SPEC_MAX_NEW = int(os.environ.get("SVB_SPEC_MAX_NEW", "60"))


def _spec_lm(max_batch=4, fat=None):
    """(cfg, params, draft_cfg, draft_params) for the speculative
    bench: target = 3 layers (layer 0 thin, deep layers fat and
    damped); draft = layer 0 plus the shared embedding/head — a strict
    parameter subset, so draft quality comes from the damping, not
    from any training step the bench would have to carry."""
    import re as _re

    from paddle_tpu.serving import tiny_lm
    from paddle_tpu.serving.generative import LMConfig

    kw = dict(vocab=SPEC_VOCAB, d_model=SPEC_DMODEL,
              n_heads=SPEC_HEADS, n_layers=3, d_ff=256,
              block_size=GEN_BLOCK, max_blocks=GEN_MAX_BLOCKS,
              max_batch=max_batch)
    cfg, params = tiny_lm(SPEC_SEED, **kw)
    fat = SPEC_FAT if fat is None else fat
    rng = np.random.RandomState(99)
    for layer in (1, 2):
        params["l%d.w1" % layer] = (
            rng.randn(SPEC_DMODEL, fat) * 0.1).astype(np.float32)
        params["l%d.w2" % layer] = (
            rng.randn(fat, SPEC_DMODEL) * 0.1 * SPEC_DAMP
        ).astype(np.float32)
        params["l%d.wo" % layer] = params["l%d.wo" % layer] * SPEC_DAMP
    dcfg = LMConfig(**dict(kw, n_layers=1))
    dparams = {k: v for k, v in params.items()
               if not _re.match(r"l[0-9]+\.", k)
               or k.startswith("l0.")}
    return cfg, params, dcfg, dparams


def _solo_loop(eng, cfg, prompt, max_new, spec=False):
    """Closed-loop single-sequence generation at the engine level (no
    server thread in the measured path): the solo decode floor both
    spec numbers quote.  Returns (tokens, rounds) where ``rounds``
    carries the per-round accepted-draft counts when ``spec``."""
    from concurrent.futures import Future

    from paddle_tpu.serving.batcher import TokenScheduler
    from paddle_tpu.serving.generative import GenRequest

    k = eng.spec_k
    req = GenRequest(prompt, max_new, None, Future())
    req.blocks = eng.pool.alloc(eng.pool.blocks_for(len(prompt)))
    req.out.append(eng.prefill(req))
    sched = TokenScheduler(eng.pool, cfg.max_batch)
    rounds = []
    need = (k + 1) if spec else 1
    while len(req.out) < max_new \
            and req.context_len + need <= cfg.max_seq:
        cap = len(req.blocks) * cfg.block_size
        while req.context_len + need > cap:
            if not sched.grow(req):
                raise RuntimeError("kv pool exhausted")
            cap += cfg.block_size
        if spec:
            toks = eng.spec_decode([req])[0]
            rounds.append(len(toks) - 1)
            for t in toks:
                if len(req.out) < max_new:
                    req.out.append(int(t))
        else:
            req.out.append(int(eng.decode([req])[0]))
    toks = list(req.out)
    eng.free_sequence(req)
    return toks, rounds


def _gen_spec_parity(steps, k=None, fat=None):
    """Greedy-parity certificate for speculative decoding (the ISSUE
    19 extension of the int8 certificate): the spec engine's token
    stream must be BIT-IDENTICAL to plain greedy decode on the same
    LM, and the per-round acceptance accounting must add up exactly —
    every emitted token is either a verified draft token or the verify
    pass's own correction/bonus token, so the emitted count equals
    1 (prefill) + sum(m_i + 1) over rounds, modulo the final-round
    max_new cap.  The measured accept-rate rides the record as an
    efficiency number; it is never a correctness input."""
    from paddle_tpu.serving.generative import GenerativeEngine

    k = SPEC_K if k is None else k
    cfg, params, dcfg, dparams = _spec_lm(fat=fat)
    prompt = np.random.RandomState(1000 + SPEC_SEED) \
        .randint(0, SPEC_VOCAB, size=8).tolist()
    eng = GenerativeEngine(cfg, params, kv_blocks=64, warm=False,
                           name="specparity-plain", prefix_cache=False,
                           spec_k=0)
    try:
        plain, _ = _solo_loop(eng, cfg, prompt, steps)
    finally:
        eng.close()
    eng = GenerativeEngine(cfg, params, kv_blocks=64, warm=False,
                           name="specparity", prefix_cache=False,
                           spec_k=k, draft=(dcfg, dparams))
    try:
        spec, rounds = _solo_loop(eng, cfg, prompt, steps, spec=True)
    finally:
        eng.close()
    n = min(len(plain), len(spec))
    identical = bool(plain[:n] == spec[:n] and n == steps)
    accepted = sum(rounds)
    proposed = k * len(rounds)
    emitted = 1 + accepted + len(rounds)
    accounting_ok = len(spec) <= emitted <= len(spec) + k
    return {
        "steps": steps, "k": k,
        "token_parity": "%d/%d" % (
            sum(a == b for a, b in zip(plain, spec)), n),
        "identical": identical,
        "rounds": len(rounds), "accepted": accepted,
        "proposed": proposed,
        "accept_rate": round(accepted / proposed, 4) if proposed
        else 0.0,
        "accounting_ok": bool(accounting_ok),
        "certified": bool(identical and accounting_ok),
        "acceptance": "greedy longest-matching-prefix + correction "
                      "token (lossless for greedy decode by "
                      "construction; this record MEASURES it)",
    }


def _run_spec(quick):
    """Solo-floor speculative phase: plain greedy tokens/s vs
    spec-decode tokens/s on the same LM and prompt, best-of-N closed
    loops after an unmeasured warm-up (engine compiles land there).
    Accept-rate and draft-overhead come from the serve_spec_* metric
    counters, so the observable numbers are also smoke-tested."""
    from paddle_tpu.observability import metrics
    from paddle_tpu.serving.generative import GenerativeEngine

    fat = int(os.environ.get("SVB_SPEC_FAT_QUICK", "512")) if quick \
        else SPEC_FAT
    k = min(SPEC_K, 4) if quick else SPEC_K
    max_new = 24 if quick else SPEC_MAX_NEW
    trials = 2 if quick else 3
    cfg, params, dcfg, dparams = _spec_lm(fat=fat)
    prompt = np.random.RandomState(1000 + SPEC_SEED) \
        .randint(0, SPEC_VOCAB, size=8).tolist()

    def best_of(fn):
        fn()
        # the warm pass above absorbed the engine compiles — rebase
        # the spec timing counters so draft-overhead reflects steady
        # state, not jit time
        metrics.zero_all()
        best, out = None, None
        for _ in range(trials):
            t0 = time.perf_counter()
            out = fn()
            dt = time.perf_counter() - t0
            best = dt if best is None or dt < best else best
        return out, best

    eng = GenerativeEngine(cfg, params, kv_blocks=64, warm=False,
                           name="specbench-plain", prefix_cache=False,
                           spec_k=0)
    try:
        plain_toks, dt_p = best_of(
            lambda: _solo_loop(eng, cfg, prompt, max_new)[0])
    finally:
        eng.close()
    plain_tps = len(plain_toks) / dt_p

    eng = GenerativeEngine(cfg, params, kv_blocks=64, warm=False,
                           name="specbench", prefix_cache=False,
                           spec_k=k, draft=(dcfg, dparams))
    try:
        spec_toks, dt_s = best_of(
            lambda: _solo_loop(eng, cfg, prompt, max_new,
                               spec=True)[0])
    finally:
        eng.close()
    spec_tps = len(spec_toks) / dt_s
    snap = metrics.snapshot()

    def _c(name):
        ent = snap.get(name)
        return ent["value"] if ent else 0

    proposed = _c("serve_spec_proposed_total")
    accepted = _c("serve_spec_accepted_total")
    draft_us = _c("serve_spec_draft_us_total")
    verify_us = _c("serve_spec_verify_us_total")
    accept = accepted / proposed if proposed else 0.0
    overhead = draft_us / (draft_us + verify_us) \
        if draft_us + verify_us else 0.0
    cert = _gen_spec_parity(
        int(os.environ.get("SVB_SPEC_PARITY_STEPS",
                           "24" if quick else "48")), k=k, fat=fat)
    speedup = round(spec_tps / max(plain_tps, 1e-9), 2)
    # quick runs keep the parity guarantee but only a collapse floor
    # on speed — a seconds-long smoke is not a perf measurement
    floor_x = float(os.environ.get("SVB_SPEC_FLOOR_X",
                                   "0.5" if quick else "2.0"))
    return {
        "model": {"vocab": SPEC_VOCAB, "d_model": SPEC_DMODEL,
                  "n_heads": SPEC_HEADS, "n_layers": 3,
                  "d_ff_thin": 256, "d_ff_fat": fat,
                  "deep_damp": SPEC_DAMP, "seed": SPEC_SEED,
                  "draft": "layer 0 + embed/head (1 layer)"},
        "k": k, "max_new_tokens": max_new, "trials": trials,
        "plain": {"tokens": len(plain_toks),
                  "tokens_s": round(plain_tps, 1)},
        "spec": {"tokens": len(spec_toks),
                 "tokens_s": round(spec_tps, 1),
                 "rounds": _c("serve_spec_rounds_total"),
                 "accept_rate": round(accept, 4),
                 "draft_overhead_pct": round(100.0 * overhead, 1),
                 "draft_us": draft_us, "verify_us": verify_us},
        "speedup_vs_plain": speedup,
        "floor_x": floor_x,
        "parity": cert,
        "ok": bool(cert["certified"] and speedup >= floor_x),
    }


PFX_USERS = int(os.environ.get("SVB_PFX_USERS", "12"))
PFX_SHARED = int(os.environ.get("SVB_PFX_SHARED", "120"))
# wider MLP than the generate-phase LM: prefill must be COMPUTE-bound
# for the suffix-only dispatch to show its win — on the CPU fallback
# the paged K/V gather costs rows x max_blocks regardless of how many
# tokens were cached, so a skinny model measures the gather, not the
# avoided FLOPs
PFX_DFF = int(os.environ.get("SVB_PFX_DFF", "2048"))


def _run_prefix(quick):
    """Multi-tenant shared-prefix trace: ``users`` tenants whose
    prompts share a long system prefix, swept over 80/90/95% shared
    mixes, prefix cache OFF vs ON.  Reports the prefill FLOPs avoided
    (from the serve_prefix_tokens_* counters — prefill compute is
    linear in tokens actually computed), TTFT p50 both ways, and the
    peak KV bytes per user (shared blocks count ONCE under refcount
    semantics).  Each mode runs one unmeasured warm trace first so
    bucket compiles never land inside a measured TTFT; the shared
    prefix is deliberately block-unaligned so the partial-tail
    copy-on-write path is on the measured path, not just in tests."""
    from concurrent.futures import Future

    from paddle_tpu.observability import metrics
    from paddle_tpu.serving import tiny_lm
    from paddle_tpu.serving.generative import (GenRequest,
                                               GenerativeEngine)

    users = 6 if quick else PFX_USERS
    shared_len = PFX_SHARED
    # prompts run ~150 tokens (shared prefix + per-tenant suffix), so
    # the prefix phase carries its own max_blocks rather than the
    # generate phase's 8-block sequences
    max_blocks = 16
    kv = int(os.environ.get(
        "SVB_PFX_KV_BLOCKS", "72" if quick else "160"))
    rng = np.random.RandomState(21)
    shared = rng.randint(0, GEN_VOCAB, size=shared_len).tolist()
    cfg, params = tiny_lm(GEN_SEED, vocab=GEN_VOCAB,
                          d_model=GEN_DMODEL, n_heads=GEN_HEADS,
                          n_layers=GEN_LAYERS, d_ff=PFX_DFF,
                          block_size=GEN_BLOCK, max_blocks=max_blocks,
                          max_batch=4)
    block_bytes = cfg.n_layers * 2 * cfg.block_size * cfg.d_model * 4

    def run_mode(prompts, on):
        eng = GenerativeEngine(cfg, params, kv_blocks=kv, warm=False,
                               name="pfx-%s" % ("on" if on else "off"),
                               prefix_cache=on, spec_k=0)
        try:
            def trace():
                reqs, ttfts, firsts = [], [], []
                for p in prompts:
                    req = GenRequest(p, 4, None, Future())
                    t0 = time.perf_counter()
                    if eng.prefix_cache is not None:
                        if not eng.prefix_cache.acquire(req):
                            raise RuntimeError("prefix admission "
                                               "failed")
                    else:
                        req.blocks = eng.pool.alloc(
                            eng.pool.blocks_for(len(p)))
                        if req.blocks is None:
                            raise RuntimeError("kv pool exhausted")
                    tok = eng.prefill(req)
                    if eng.prefix_cache is not None:
                        eng.prefix_cache.insert(req)
                    ttfts.append((time.perf_counter() - t0) * 1e3)
                    firsts.append(int(tok))
                    reqs.append(req)
                # snapshot while every tenant is LIVE: the shared
                # gauge reads sharing as it exists under load, not
                # after the drain parks everything at refcount zero
                peak = eng.pool.used_blocks
                snap = metrics.snapshot()
                for req in reqs:
                    eng.free_sequence(req)
                return ttfts, firsts, peak, snap

            # warm TWICE: the first trace fills the trie (and, cache
            # on, runs the cold COW path), the second hits the exact
            # steady-state suffix buckets the measured trace will use
            # — a bucket first compiled inside a measured TTFT, or a
            # background compile still churning on a small box, would
            # be harness noise dressed up as cache overhead
            metrics.zero_all()
            trace()
            cold = metrics.snapshot()
            trace()
            time.sleep(1.5)
            metrics.zero_all()
            ttfts, firsts, peak, snap = trace()
            # COW fires on the COLD trace (divergent suffixes sharing
            # a partial block); the measured steady-state trace is an
            # exact repeat, so its counter would hide it
            snap = dict(snap, _cow_cold=cold[
                "serve_kv_cow_copies_total"]["value"])
        finally:
            eng.close()
        return ttfts, firsts, peak, snap

    out_mixes = []
    for mix in (80, 90, 95):
        suffix_len = max(1, int(round(
            shared_len * (100.0 / mix - 1.0))))
        prompts = [shared + rng.randint(
            0, GEN_VOCAB, size=suffix_len).tolist()
            for _ in range(users)]
        ttf_off, first_off, peak_off, _ = run_mode(prompts, on=False)
        ttf_on, first_on, peak_on, snap = run_mode(prompts, on=True)
        tok_total = snap["serve_prefix_tokens_total"]["value"]
        tok_cached = snap["serve_prefix_tokens_cached_total"]["value"]
        avoided = 100.0 * tok_cached / tok_total if tok_total else 0.0
        p50_off = _pctl(sorted(ttf_off), 50)
        p50_on = _pctl(sorted(ttf_on), 50)
        out_mixes.append({
            "mix_pct": mix, "users": users,
            "prompt_tokens": len(prompts[0]),
            "shared_tokens": shared_len,
            "prefix_hits": snap["serve_kv_prefix_hits"]["value"],
            "prefill_tokens": tok_total,
            "prefill_tokens_cached": tok_cached,
            "prefill_flops_avoided_pct": round(avoided, 1),
            "ttft_p50_ms": {"off": round(p50_off, 3),
                            "on": round(p50_on, 3)},
            "ttft_speedup": round(p50_off / max(p50_on, 1e-9), 2),
            "kv_blocks_peak": {"off": peak_off, "on": peak_on},
            "kv_bytes_per_user": {
                "off": int(peak_off * block_bytes / users),
                "on": int(peak_on * block_bytes / users)},
            "blocks_shared": snap["serve_kv_blocks_shared"]["value"],
            "cow_copies_cold_trace": snap["_cow_cold"],
            "cow_copies": snap["serve_kv_cow_copies_total"]["value"],
            "tokens_identical": bool(first_off == first_on),
        })
    ok = all(m["tokens_identical"]
             and m["kv_blocks_peak"]["on"] < m["kv_blocks_peak"]["off"]
             and m["prefill_flops_avoided_pct"]
             >= 0.75 * m["mix_pct"]
             and m["ttft_p50_ms"]["on"] <= m["ttft_p50_ms"]["off"]
             for m in out_mixes)
    return {"users": users, "shared_tokens": shared_len,
            "kv_block_bytes": block_bytes, "mixes": out_mixes,
            "ok": bool(ok)}


def _run_generate(quick, seconds, max_batch):
    from paddle_tpu.observability import metrics
    from paddle_tpu.serving import InferenceServer

    kv_blocks = int(os.environ.get("SVB_GEN_KV_BLOCKS",
                                   "128" if quick else "512"))
    max_new = int(os.environ.get("SVB_GEN_MAX_NEW",
                                 "16" if quick else "32"))
    cfg, params, kv = _gen_cfg(max_batch, kv_blocks)
    rng = np.random.RandomState(5)
    prompts = _gen_prompts(rng, 64)
    # feature knobs (the tier-1 smoke parametrizes over these): run
    # the SAME Poisson trace with the prefix cache on and/or a draft
    # LM speculating — correctness under load, not a perf claim
    prefix_on = os.environ.get("SVB_GEN_PREFIX_CACHE", "") == "1"
    spec_k = int(os.environ.get("SVB_GEN_SPEC_K", "0"))
    draft = None
    if spec_k:
        import re as _re

        from paddle_tpu.serving.generative import LMConfig

        dcfg = LMConfig(vocab=GEN_VOCAB, d_model=GEN_DMODEL,
                        n_heads=GEN_HEADS, n_layers=1, d_ff=GEN_DFF,
                        block_size=GEN_BLOCK,
                        max_blocks=GEN_MAX_BLOCKS,
                        max_batch=max_batch)
        draft = (dcfg, {k: v for k, v in params.items()
                        if not _re.match(r"l[0-9]+\.", k)
                        or k.startswith("l0.")})
    if prefix_on:
        # give the trace something to share: one block-sized system
        # prefix on every prompt, so admission-time lookups hit
        common = rng.randint(0, GEN_VOCAB, size=GEN_BLOCK).tolist()
        prompts = [common + p for p in prompts]
    srv = InferenceServer()
    t_load = time.perf_counter()
    eng = srv.load_generative("g", cfg, params, kv_blocks=kv,
                              prefix_cache=True if prefix_on else None,
                              spec_k=spec_k or None, draft=draft)
    load_s = time.perf_counter() - t_load
    try:
        floor = _gen_floor(srv, prompts[0], max(max_new, 32))
        cap_tokens_s = _gen_capacity(
            srv, prompts[:4 * max_batch], max_new)
        # offered rate: high enough that the decode batch stays full
        # (the occupancy acceptance), low enough for a steady state —
        # 0.85 of the measured full-batch token capacity
        rate_rps = 0.85 * cap_tokens_s / max_new
        metrics.zero_all()
        poisson, drop = _gen_poisson(srv, prompts, max_new,
                                     2 * seconds, rate_rps)
        snap = metrics.snapshot()
        rows = snap["serve_decode_rows_total"]["value"]
        slots = snap["serve_decode_slots_total"]["value"]
        steps_n = snap["serve_decode_steps_total"]["value"]
        occupancy = {
            # live rows / dispatched bucket rows: padding waste — the
            # acceptance metric (a drained batch re-buckets down, so
            # sustained high mean needs admission keeping rows IN the
            # batch while prefills stream)
            "mean_pct": round(100.0 * rows / slots, 1) if slots else 0.0,
            "p50_pct": snap["serve_decode_occupancy_pct"]["p50"],
            "buckets": snap["serve_decode_occupancy_pct"]["buckets"],
            "decode_steps": steps_n,
            # absolute concurrency, for honesty alongside the bucket-
            # relative number: mean live rows per iteration and the
            # same as a fraction of the configured batch ceiling (a
            # function of offered load, not an engine property — the
            # Poisson rate targets 0.85x capacity, not full batches)
            "mean_rows": round(rows / steps_n, 2) if steps_n else 0.0,
            "utilization_vs_max_batch_pct": round(
                100.0 * rows / (steps_n * max_batch), 1)
            if steps_n else 0.0,
            "prefills": snap["serve_prefills_total"]["value"],
        }
        kv_stats = {
            # capacity from the live pool: metrics.zero_all() above
            # rebased the gauges to measure the phase, not the load
            "blocks_total": eng.pool.capacity,
            "blocks_used_after_drain": eng.pool.used_blocks,
            "blocks_cached_after_drain": eng.pool.cached_blocks,
            "alloc_failures":
                snap["serve_kv_alloc_failures_total"]["value"],
            "preemptions": snap["serve_kv_preemptions_total"]["value"],
        }
        features = {"prefix_cache": prefix_on, "spec_k": spec_k}
        if prefix_on:
            features["prefix_hits"] = \
                snap["serve_kv_prefix_hits"]["value"]
            features["prefix_tokens_cached"] = \
                snap["serve_prefix_tokens_cached_total"]["value"]
        if spec_k:
            prop = snap["serve_spec_proposed_total"]["value"]
            acc = snap["serve_spec_accepted_total"]["value"]
            features["spec_rounds"] = \
                snap["serve_spec_rounds_total"]["value"]
            features["spec_accept_rate"] = \
                round(acc / prop, 4) if prop else 0.0
    finally:
        srv.close()
    int8 = _gen_int8_parity(max_batch, kv_blocks,
                            int(os.environ.get("SVB_GEN_PARITY_STEPS",
                                               "64")))
    speedup = round(poisson["tokens_s"] / max(floor["tokens_s"], 1e-9),
                    2)
    return {
        "model": {"vocab": GEN_VOCAB, "d_model": GEN_DMODEL,
                  "n_heads": GEN_HEADS, "n_layers": GEN_LAYERS,
                  "d_ff": GEN_DFF, "seed": GEN_SEED,
                  "block_size": GEN_BLOCK,
                  "max_blocks": GEN_MAX_BLOCKS,
                  "kv_blocks": kv_blocks},
        "max_batch": max_batch,
        "max_new_tokens": max_new,
        "features": features,
        "load_warm_s": round(load_s, 2),
        "floor": floor,
        "capacity_tokens_s": round(cap_tokens_s, 1),
        "poisson": poisson,
        "speedup_tokens_vs_floor": speedup,
        "occupancy": occupancy,
        "kv": kv_stats,
        "drop": drop,
        "int8": int8,
        "ok": bool(drop["zero_dropped"] and int8["parity_ok"]
                   and int8["certified"]
                   and occupancy["mean_pct"] >= 80.0),
    }


def _wire_sanity(srv, x):
    """One request over the socket endpoint — the fastwire-framed
    Predict method answers and matches the in-process result."""
    from paddle_tpu.serving import PredictClient

    port = srv.start_endpoint()
    with PredictClient("127.0.0.1", port) as cli:
        t0 = time.perf_counter()
        outs = cli.predict("m", {"x": x})
        lat = time.perf_counter() - t0
    ref = srv.predict("m", {"x": x})
    ok = all(np.allclose(outs[k], ref[k], atol=1e-5) for k in outs)
    return {"ok": bool(ok), "latency_ms": round(lat * 1e3, 3),
            "port": port}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="seconds-long tier-1 smoke (CPU)")
    ap.add_argument("--out", default="",
                    help="also write the JSON to this file")
    ap.add_argument("--rate-x", type=float, default=4.0,
                    help="poisson offered rate as a multiple of the "
                         "measured floor QPS")
    ap.add_argument("--seconds", type=float, default=0.0,
                    help="override per-phase duration")
    ap.add_argument("--mode",
                    choices=("predict", "generate", "prefix", "spec",
                             "all"),
                    default="all",
                    help="which serving planes to bench: the PR 9 "
                         "predict phases, the ISSUE 11 token-level "
                         "generate phases, the ISSUE 19 shared-prefix "
                         "trace or speculative solo-floor phases, or "
                         "all of them (default)")
    ap.add_argument("--sentinel", action="store_true",
                    help="gate this run against PERF_TRAJECTORY.json "
                         "via tools/perf_sentinel.py (rc 3 on a >15%% "
                         "regression vs the recorded floor; quick "
                         "runs only compare against quick floors).  "
                         "ROADMAP: always pass this")
    args = ap.parse_args(argv)

    import tempfile

    from paddle_tpu.core.flags import FLAGS, apply_xla_flags
    from paddle_tpu.inference import aot as aot_mod
    from paddle_tpu.observability import metrics
    from paddle_tpu.serving import InferenceServer

    apply_xla_flags()
    seconds = args.seconds or (1.0 if args.quick else 6.0)
    max_batch = int(os.environ.get("SVB_MAX_BATCH",
                                   "8" if args.quick else "16"))
    max_wait_us = int(os.environ.get("SVB_MAX_WAIT_US", "2000"))

    def _finish(out):
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        rc = 0 if out["ok"] else 1
        return rc or (_sentinel_check(out) if args.sentinel else 0)

    if args.mode in ("generate", "prefix", "spec"):
        rec = {"generate": lambda: _run_generate(args.quick, seconds,
                                                 max_batch),
               "prefix": lambda: _run_prefix(args.quick),
               "spec": lambda: _run_spec(args.quick)}[args.mode]()
        return _finish({
            "metric": "serve_bench", "quick": bool(args.quick),
            "mode": args.mode,
            "platform": os.environ.get("JAX_PLATFORMS", ""),
            args.mode: rec, "ok": rec["ok"]})

    tmp = tempfile.mkdtemp(prefix="serve_bench_")
    d1, d2 = os.path.join(tmp, "v1"), os.path.join(tmp, "v2")
    t_build = time.perf_counter()
    _build_and_save(d1, 11, max_batch)
    _build_and_save(d2, 911, max_batch)
    build_s = time.perf_counter() - t_build
    x = np.linspace(-1, 1, D_IN).astype(np.float32).reshape(1, D_IN)

    floor = _measure_floor(d1, x, seconds)

    metrics.zero_all()
    srv = InferenceServer(max_batch=max_batch, max_wait_us=max_wait_us)
    t_load = time.perf_counter()
    srv.load("m", d1)
    load_s = time.perf_counter() - t_load
    ref_v1 = np.asarray(next(iter(srv.predict("m", {"x": x}).values())))

    saturated = _measure_saturated(srv, x, seconds,
                                   window=4 * max_batch)
    metrics.zero_all()
    # open-loop offered rate: rate_x x floor, capped under the probed
    # capacity — an open-loop rate above capacity has no steady state
    # (the queue and p99 grow without bound for as long as you let it)
    rate = min(args.rate_x * floor["qps"], 0.65 * saturated["qps"])
    # headline phase: steady open-loop load, no configuration churn
    poisson, steady_drop = _poisson(srv, x, ref_v1, 2 * seconds, rate)
    # swap phase: same load while swap() builds + flips to v2 — the
    # shadow compile competes for the host, so its latency spike is
    # reported HERE, not folded into the steady-state headline
    poisson_swap, swap = _poisson(srv, x, ref_v1, 2 * seconds, rate,
                                  seed=13, swap_to=d2, swap_at=0.33)
    swap["steady_phase_dropped"] = steady_drop["dropped"]
    snap = metrics.snapshot()
    occupancy = snap["serve_batch_occupancy"]
    phases = {k: {"p50_ms": snap[k]["p50"], "p99_ms": snap[k]["p99"],
                  "count": snap[k]["count"]}
              for k in ("serve_queue_wait_ms", "serve_batch_assemble_ms",
                        "serve_dispatch_ms")}
    wire = _wire_sanity(srv, x)
    srv.close()

    speedup = round(poisson["qps"] / max(floor["qps"], 1e-9), 2)
    speedup_saturated = round(
        saturated["qps"] / max(floor["qps"], 1e-9), 2)
    p99_budget_ms = max(2.0 * floor["p99_ms"], 10.0)
    out = {
        "metric": "serve_bench",
        "quick": bool(args.quick),
        "platform": os.environ.get("JAX_PLATFORMS", ""),
        "model": {"d_in": D_IN, "hidden": HIDDEN, "d_out": D_OUT},
        "max_batch": max_batch,
        "max_wait_us": max_wait_us,
        "build_s": round(build_s, 2),
        "load_warm_s": round(load_s, 2),
        "floor": floor,
        "saturated": saturated,
        "poisson": poisson,
        "poisson_under_swap": poisson_swap,
        "speedup_vs_floor": speedup,
        "speedup_saturated_vs_floor": speedup_saturated,
        "p99_budget_ms": round(p99_budget_ms, 3),
        "within_p99_budget": poisson["p99_ms"] <= p99_budget_ms,
        "batch_occupancy": {"count": occupancy["count"],
                            "p50": occupancy["p50"],
                            "buckets": occupancy["buckets"]},
        "phases": phases,
        "swap": swap,
        "wire": wire,
        "aot_load_fallback_total":
            metrics.counter("aot_load_fallback_total").value,
        "aot_load_fallbacks": list(aot_mod.FALLBACKS),
        "ok": bool(speedup >= 3.0
                   and poisson["p99_ms"] <= p99_budget_ms
                   and steady_drop["zero_dropped"]
                   and swap["zero_dropped"] and swap["torn"] == 0
                   and wire["ok"]),
    }
    if args.mode == "all":
        gen = _run_generate(args.quick, seconds, max_batch)
        out["generate"] = gen
        pfx = _run_prefix(args.quick)
        out["prefix"] = pfx
        spec = _run_spec(args.quick)
        out["spec"] = spec
        out["ok"] = bool(out["ok"] and gen["ok"] and pfx["ok"]
                         and spec["ok"])
    return _finish(out)


def _sentinel_check(out):
    """Perf sentinel (ISSUE 13): gate the fresh run against the
    recorded PERF_TRAJECTORY.json floors; rc 3 (and a one-line JSON
    report) on regression."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from perf_sentinel import sentinel_gate

    return sentinel_gate(out)


if __name__ == "__main__":
    sys.exit(main())
