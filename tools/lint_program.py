#!/usr/bin/env python
"""Lint a saved ProgramDesc / inference model ahead of any execution.

Runs the paddle_tpu/analysis checker pipeline (the same one the
executor runs on a compile-cache miss) over a serialized program and
prints structured diagnostics — so a model exported on one machine can
be gated in CI before it ever reaches a TPU.

Usage:
    python tools/lint_program.py MODEL            # dir or proto file
    python tools/lint_program.py MODEL --json     # machine-readable
    python tools/lint_program.py MODEL --checkers def-use,lifetime
    python tools/lint_program.py MODEL --max-level warning
    python tools/lint_program.py --list-checkers  # registered names
    python tools/lint_program.py --scan-sources paddle_tpu/serving \\
        paddle_tpu/distributed               # AST source checkers
                                             # (e.g. 'rawlock')

MODEL is either a file holding a serialized framework ProgramDesc proto
(e.g. the ``__model__`` written by fluid.io.save_inference_model) or a
directory containing one (``--model-filename`` overrides the name).

Exit status: 0 clean (or findings below --max-level), 1 when findings
at or above --max-level exist, 2 when the input cannot be parsed.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def load_program(path, model_filename):
    from paddle_tpu.core.desc import ProgramDesc

    if os.path.isdir(path):
        path = os.path.join(path, model_filename)
    with open(path, "rb") as f:
        data = f.read()
    return ProgramDesc.parse_from_string(data), path


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="lint a saved ProgramDesc / inference model")
    ap.add_argument("model", nargs="?", default=None,
                    help="proto file or model directory")
    ap.add_argument("--model-filename", default="__model__",
                    help="proto name inside a model directory")
    ap.add_argument("--checkers", default=None,
                    help="comma-separated checker names (default: all; "
                         "explicit names override FLAGS_check_suppress)")
    ap.add_argument("--list-checkers", action="store_true",
                    help="print every registered checker (incl. "
                         "'lifetime', the ISSUE 14 donation checker) "
                         "with its one-line description and exit")
    ap.add_argument("--scan-sources", nargs="+", default=None,
                    metavar="PATH",
                    help="run the AST source checkers (e.g. 'rawlock') "
                         "over .py files/trees instead of linting a "
                         "ProgramDesc; honors --checkers/--json/"
                         "--max-level")
    ap.add_argument("--max-level", default="error",
                    choices=["error", "warning", "note"],
                    help="exit non-zero when findings at or above this "
                         "severity exist (default: error)")
    ap.add_argument("--json", action="store_true",
                    help="emit diagnostics as a JSON array")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the per-diagnostic lines; summary only")
    args = ap.parse_args(argv)

    # ops must be registered before checkers consult the registry
    import paddle_tpu.fluid  # noqa: F401
    from paddle_tpu import analysis
    from paddle_tpu.analysis.diagnostics import Severity

    if args.list_checkers:
        for name, fn in analysis.CHECKERS.items():
            doc = (fn.__doc__ or "").strip().splitlines()
            print("%-18s %s" % (name, doc[0] if doc else ""))
        for name, fn in analysis.SOURCE_CHECKERS.items():
            doc = (fn.__doc__ or "").strip().splitlines()
            print("%-18s %s" % (name + " (src)", doc[0] if doc else ""))
        return 0

    checkers = ([c.strip() for c in args.checkers.split(",") if c.strip()]
                if args.checkers else None)

    if args.scan_sources is not None:
        diags = analysis.run_source_checkers(
            args.scan_sources, root=REPO, checkers=checkers)
        if args.json:
            print(json.dumps([d.to_dict() for d in diags], indent=2))
        else:
            for d in diags:
                print(d.format())
            print("scan-sources: %d finding(s) over %s"
                  % (len(diags), ", ".join(args.scan_sources)))
        threshold = Severity.rank(args.max_level)
        return 1 if any(Severity.rank(d.severity) >= threshold
                        for d in diags) else 0

    if args.model is None:
        ap.error("MODEL is required unless --list-checkers or "
                 "--scan-sources is given")

    try:
        program, path = load_program(args.model, args.model_filename)
    except Exception as e:
        print("lint_program: cannot load %r: %s" % (args.model, e),
              file=sys.stderr)
        return 2

    diags = analysis.verify_program(program, checkers)

    if args.json:
        print(json.dumps([d.to_dict() for d in diags], indent=2))
    elif not args.quiet:
        for d in diags:
            print(d.format())

    counts = {Severity.ERROR: 0, Severity.WARNING: 0, Severity.NOTE: 0}
    for d in diags:
        counts[d.severity] = counts.get(d.severity, 0) + 1
    if not args.json:
        print("%s: %d block(s), %d op(s): %d error(s), %d warning(s), "
              "%d note(s)"
              % (path, len(program.blocks),
                 sum(len(b.ops) for b in program.blocks),
                 counts[Severity.ERROR], counts[Severity.WARNING],
                 counts[Severity.NOTE]))

    threshold = Severity.rank(args.max_level)
    failing = sum(1 for d in diags
                  if Severity.rank(d.severity) >= threshold)
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
