#!/usr/bin/env python
"""Merge telemetry trace dumps and print a per-phase step-time
breakdown (ISSUE 6 tentpole c — the timeline.py analog for the new
telemetry layer).

Inputs are the per-process dump files the tracer writes
(``trace_<label>_<pid>.json`` under FLAGS_telemetry_dump_dir, or any
``Tracer.dump`` output; a previously merged chrome trace also loads).
Device traces from a ``jax.profiler.trace`` capture dir merge in with
``--xplane`` (utils/xplane.py parses them; XLine timestamps are
unix-epoch, so they land on the host spans' wall-clock timeline).

Usage:
    python tools/trace_report.py DUMP.json [DUMP2.json ...]
    python tools/trace_report.py DUMPS... --merge merged_trace.json
    python tools/trace_report.py DUMPS... --xplane /tmp/xprof_capture
    python tools/trace_report.py DUMPS... --prefix step. --top 20
    python tools/trace_report.py DUMPS... --numerics   # grad-norm
        rollup per process; numerics_*.json trip artifacts passed as
        inputs are summarized (first bad op, round cid, recent losses)
    python tools/trace_report.py DUMPS... --all        # every rollup

--merge writes one chrome://tracing JSON: each process is a chrome
pid named by its label, and spans of the same sync round share a
``cid`` arg ((round, sender, seq) wire identity) — select one in the
viewer to correlate a trainer's send/barrier/get with the pserver's
scatter/apply for that round.

Per-subsystem rollups are table-registry driven (ROLLUPS below): each
entry names its flag, the export.py rows/format pair and its section
title, so a new subsystem adds ONE registry row instead of another
copy-paste dispatch branch (ISSUE 13 satellite; rollups had been
copy-pasted per flag since PR 7).  ``--all`` implies ``--kernels``
plus every registry rollup.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# one row per rollup: (flag/attr name, export rows fn, export format
# fn, text-mode section title, --help text).  Everything downstream —
# argparse registration, --all, the JSON wrap and the text sections —
# iterates this table.
ROLLUPS = (
    ("numerics", "numerics_rows", "format_numerics_table",
     "numerics rollup (grad-norm trend / nonfinite sightings per "
     "process):",
     "print the numerics-observatory rollup (grad-norm trend, param "
     "absmax, nonfinite counts per process — ISSUE 8); "
     "numerics_*.json trip artifacts may also be passed as inputs "
     "and are summarized"),
    ("wire", "wire_rows", "format_wire_table",
     "wire rollup (grad compression / fastwire traffic / staleness "
     "per process):",
     "print the pserver wire/compression rollup (grad bytes raw vs "
     "on-wire, codec encode time, fastwire traffic, staleness gap "
     "per process — ISSUE 10)"),
    ("serve", "serve_rows", "format_serve_table",
     "serve rollup (requests/tokens / decode occupancy / TTFT+ITL / "
     "paged KV pressure / prefix-cache + speculative columns per "
     "process):",
     "print the serving-tier rollup (requests/tokens, decode-batch "
     "occupancy, TTFT and inter-token latency, paged KV cache "
     "pressure: blocks used/total, allocation failures, preemptions "
     "— ISSUE 11; plus the ISSUE 19 columns: prefix-hit-rate, "
     "blocks shared, speculative accept-rate, draft overhead)"),
    ("scale", "scale_rows", "format_scale_table",
     "scale rollup (resource ledgers per process: pending grads / "
     "caches+evictions / barrier quorum / apply backlog):",
     "print the scale-observatory rollup (resource ledgers per "
     "process: pending-grad footprint, reply/replay cache bytes + "
     "evictions, barrier set, apply backlog, oldest-pending age, "
     "quorum scan work — ISSUE 12); flight dumps work as inputs too "
     "(their metrics snapshot carries the ledger gauges)"),
    ("slo", "slo_rows", "format_slo_table",
     "slo rollup (burn rates / budget remaining / alerts per "
     "process):",
     "print the Watchtower SLO rollup (per-spec fast/slow burn "
     "rates, error budget remaining, alert counters per process — "
     "ISSUE 13); flight dumps written by a firing alert carry the "
     "offending series too"),
    ("moe", "moe_rows", "format_moe_table",
     "moe rollup (router steps/tokens / per-expert load / dropped "
     "fraction / entropy per process):",
     "print the MoE routing rollup (capacity-factor stats from "
     "parallel/moe.py: per-expert load distribution, dropped-token "
     "fraction, router entropy per process — ISSUE 15 rider)"),
    ("weaver", "weaver_rows", "format_weaver_table",
     "weaver rollup (schedules explored/pruned / failing schedules / "
     "minimized repro length per process):",
     "print the schedule-exploration rollup (weaver explorer "
     "coverage: schedules executed, sleep-set-pruned branches, "
     "failing schedules found, minimized decision-trace length per "
     "process — ISSUE 18); tools/weaver.py leaves a dump when "
     "FLAGS_telemetry_dump_dir is set"),
)


def _print_trips(paths):
    """Summarize numerics_*.json trip artifacts: who tripped, where,
    which round/step, and the first bad op when bisect named one."""
    print("numerics trip artifacts:")
    for p in sorted(paths):
        try:
            with open(p) as f:
                rec = json.load(f)
        except Exception as e:
            print("  %s: unreadable (%s)" % (p, e))
            continue
        parts = [rec.get("reason", "?")]
        if rec.get("cid"):
            parts.append("cid=%s" % rec["cid"])
        if rec.get("sender"):
            parts.append("sender=%s" % rec["sender"])
        fbo = rec.get("first_bad_op")
        if fbo:
            parts.append("first_bad_op=%s (block %s op %s, out %s)" % (
                fbo.get("type"), fbo.get("block"), fbo.get("op_idx"),
                fbo.get("output")))
        if rec.get("trip_vars"):
            parts.append("vars=%s" % rec["trip_vars"][:4])
        losses = rec.get("losses") or []
        if losses:
            parts.append("recent_losses=%s" % [
                round(v, 4) for v in losses[-4:]])
        print("  %s: %s" % (os.path.basename(p), "  ".join(parts)))


def main(argv=None):
    from paddle_tpu.observability import export

    ap = argparse.ArgumentParser(
        description="merge telemetry dumps; print per-phase breakdown")
    ap.add_argument("dumps", nargs="+",
                    help="per-process trace dump JSON files")
    ap.add_argument("--merge", default=None, metavar="OUT.json",
                    help="write the merged chrome://tracing JSON here")
    ap.add_argument("--xplane", default=None, metavar="DIR",
                    help="jax.profiler.trace capture dir to merge "
                         "device ops from")
    ap.add_argument("--prefix", default="",
                    help="only report span names with this prefix "
                         "(e.g. 'step.' for the executor phases)")
    ap.add_argument("--top", type=int, default=0,
                    help="limit the table to the top-N phases by total")
    ap.add_argument("--json", action="store_true",
                    help="emit the breakdown rows as JSON instead")
    ap.add_argument("--kernels", action="store_true",
                    help="with --json: wrap output as {phases, kernels} "
                         "including the per-kernel rollup (text mode "
                         "always prints the rollup when kernels exist)")
    for flag, _rows, _fmt, _title, help_text in ROLLUPS:
        ap.add_argument("--" + flag, action="store_true",
                        help=help_text)
    ap.add_argument("--all", action="store_true", dest="all_rollups",
                    help="implies --kernels plus every per-subsystem "
                         "rollup (%s)" % " ".join(
                             "--" + f for f, *_ in ROLLUPS))
    args = ap.parse_args(argv)
    if args.all_rollups:
        args.kernels = True
        for flag, *_ in ROLLUPS:
            setattr(args, flag, True)

    # numerics trip artifacts ride the same dump dir as trace dumps;
    # partition them out by their fixed filename shape
    # (numerics_<pid>_<n>.json, see numerics.dump_numerics) so the
    # merge only sees real trace dumps — a multi-MB trace is never
    # json-parsed twice just to read a 'kind' key
    trips = []
    dump_paths = []
    for p in args.dumps:
        if os.path.basename(p).startswith("numerics_"):
            trips.append(p)
        else:
            dump_paths.append(p)
    if not dump_paths and trips:
        # trip-artifacts-only invocation: summarize and exit
        _print_trips(trips)
        return 0

    trace, dumps = export.merge_files(dump_paths, out_path=args.merge,
                                      xplane=args.xplane)
    rows = export.phase_rows(dumps)
    if args.prefix:
        rows = [r for r in rows if r["name"].startswith(args.prefix)]
    # per-kernel rollup (ISSUE 7): Pallas launch-site spans grouped by
    # kernel name + device events from the --xplane capture — fusion
    # wins readable straight from a telemetry dump.  Skipped in plain
    # --json mode (pre-existing contract emits bare phase rows), which
    # also spares the full extra span walk on large rings
    krows = export.kernel_rows(dumps, trace) \
        if (args.kernels or not args.json) else []
    # every registered rollup asked for: flag -> its export rows
    rollup_rows = {flag: getattr(export, rows_fn)(dumps)
                   for flag, rows_fn, _fmt, _title, _h in ROLLUPS
                   if getattr(args, flag)}
    if args.json:
        if rollup_rows or args.kernels:
            # one wrapped object, keys present for the rollups asked
            # for; bare phase rows stay the no-flag contract
            print(json.dumps(dict(
                {"phases": rows, "kernels": krows}, **rollup_rows),
                indent=2))
        else:
            print(json.dumps(rows, indent=2))
    else:
        total_spans = sum(len(d.get("spans", [])) for d in dumps)
        print("%d process dump(s), %d spans, %d trace events%s" % (
            len(dumps), total_spans, len(trace["traceEvents"]),
            (" -> %s" % args.merge) if args.merge else ""))
        open_spans = [s for d in dumps
                      for s in d.get("open_spans", [])]
        if open_spans:
            print("OPEN (never finished — where each thread was "
                  "blocked at dump time):")
            for s in open_spans:
                print("  %-32s elapsed %.1f ms  %s" % (
                    s["name"], s.get("elapsed_us", 0) / 1e3,
                    s.get("cid", "")))
        print(export.format_phase_table(rows, top=args.top))
        if krows:
            print("\nper-kernel rollup (pallas launch sites + xplane "
                  "device ops):")
            print(export.format_kernel_table(krows))
        for flag, _rows_fn, fmt_fn, title, _h in ROLLUPS:
            if not getattr(args, flag):
                continue
            print("\n" + title)
            print(getattr(export, fmt_fn)(rollup_rows[flag]))
    if trips:
        _print_trips(trips)
    if not rows:
        # a written --merge artifact — or any requested rollup that
        # produced rows (flight dumps carry metrics but no completed
        # spans) — is a success even when the span table is empty;
        # fail only when the run produced no output at all
        print("no completed spans matched", file=sys.stderr)
        return 0 if (args.merge or krows
                     or any(rollup_rows.values())) else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
