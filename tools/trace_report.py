#!/usr/bin/env python
"""Merge telemetry trace dumps and print a per-phase step-time
breakdown (ISSUE 6 tentpole c — the timeline.py analog for the new
telemetry layer).

Inputs are the per-process dump files the tracer writes
(``trace_<label>_<pid>.json`` under FLAGS_telemetry_dump_dir, or any
``Tracer.dump`` output; a previously merged chrome trace also loads).
Device traces from a ``jax.profiler.trace`` capture dir merge in with
``--xplane`` (utils/xplane.py parses them; XLine timestamps are
unix-epoch, so they land on the host spans' wall-clock timeline).

Usage:
    python tools/trace_report.py DUMP.json [DUMP2.json ...]
    python tools/trace_report.py DUMPS... --merge merged_trace.json
    python tools/trace_report.py DUMPS... --xplane /tmp/xprof_capture
    python tools/trace_report.py DUMPS... --prefix step. --top 20

--merge writes one chrome://tracing JSON: each process is a chrome
pid named by its label, and spans of the same sync round share a
``cid`` arg ((round, sender, seq) wire identity) — select one in the
viewer to correlate a trainer's send/barrier/get with the pserver's
scatter/apply for that round.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None):
    from paddle_tpu.observability import export

    ap = argparse.ArgumentParser(
        description="merge telemetry dumps; print per-phase breakdown")
    ap.add_argument("dumps", nargs="+",
                    help="per-process trace dump JSON files")
    ap.add_argument("--merge", default=None, metavar="OUT.json",
                    help="write the merged chrome://tracing JSON here")
    ap.add_argument("--xplane", default=None, metavar="DIR",
                    help="jax.profiler.trace capture dir to merge "
                         "device ops from")
    ap.add_argument("--prefix", default="",
                    help="only report span names with this prefix "
                         "(e.g. 'step.' for the executor phases)")
    ap.add_argument("--top", type=int, default=0,
                    help="limit the table to the top-N phases by total")
    ap.add_argument("--json", action="store_true",
                    help="emit the breakdown rows as JSON instead")
    ap.add_argument("--kernels", action="store_true",
                    help="with --json: wrap output as {phases, kernels} "
                         "including the per-kernel rollup (text mode "
                         "always prints the rollup when kernels exist)")
    args = ap.parse_args(argv)

    trace, dumps = export.merge_files(args.dumps, out_path=args.merge,
                                      xplane=args.xplane)
    rows = export.phase_rows(dumps)
    if args.prefix:
        rows = [r for r in rows if r["name"].startswith(args.prefix)]
    # per-kernel rollup (ISSUE 7): Pallas launch-site spans grouped by
    # kernel name + device events from the --xplane capture — fusion
    # wins readable straight from a telemetry dump.  Skipped in plain
    # --json mode (pre-existing contract emits bare phase rows), which
    # also spares the full extra span walk on large rings
    krows = export.kernel_rows(dumps, trace) \
        if (args.kernels or not args.json) else []
    if args.json:
        print(json.dumps(
            {"phases": rows, "kernels": krows} if args.kernels
            else rows, indent=2))
    else:
        total_spans = sum(len(d.get("spans", [])) for d in dumps)
        print("%d process dump(s), %d spans, %d trace events%s" % (
            len(dumps), total_spans, len(trace["traceEvents"]),
            (" -> %s" % args.merge) if args.merge else ""))
        open_spans = [s for d in dumps
                      for s in d.get("open_spans", [])]
        if open_spans:
            print("OPEN (never finished — where each thread was "
                  "blocked at dump time):")
            for s in open_spans:
                print("  %-32s elapsed %.1f ms  %s" % (
                    s["name"], s.get("elapsed_us", 0) / 1e3,
                    s.get("cid", "")))
        print(export.format_phase_table(rows, top=args.top))
        if krows:
            print("\nper-kernel rollup (pallas launch sites + xplane "
                  "device ops):")
            print(export.format_kernel_table(krows))
    if not rows:
        # a written --merge artifact is a success even when the table
        # filter matched nothing (e.g. --prefix step. on pserver-only
        # dumps); fail only when the run produced no output at all
        print("no completed spans matched", file=sys.stderr)
        return 0 if args.merge else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
