#!/usr/bin/env python
"""Drive the Weaver schedule explorer (paddle_tpu/analysis/weaver.py)
over the pserver / KV-pool / MigrateKV / router protocol scenarios.

Usage:
    python tools/weaver.py --list                     # scenario table
    python tools/weaver.py                            # explore all, HEAD
    python tools/weaver.py --scenario kv_pool --plant double_free
    python tools/weaver.py --replay weaver_kv_pool_0.json
    python tools/weaver.py --quick                    # tier-1 smoke
    python tools/weaver.py --mode random --max-schedules 2000 --seed 7

Exploration enumerates every schedule up to --preemption-bound
preemptions (DFS with sleep-set pruning; 'none' lifts the bound), or
samples seeded random walks with --mode random.  A failing schedule is
delta-debug minimized and written as a replayable
``weaver_<scenario>_<n>.json`` artifact naming the racing sites;
--replay re-executes an artifact bit-deterministically and reports
whether the pinned failure reproduced.

Exit status: 0 every explored scenario is clean (or --replay
reproduced its failure), 1 a failure was found (or --replay did not
reproduce), 2 usage error.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _out_dir(args):
    if args.out_dir:
        return args.out_dir
    from paddle_tpu.core.flags import FLAGS
    return FLAGS.telemetry_dump_dir or "."


def _dump_metrics():
    # leave one flight snapshot so trace_report.py --weaver has a
    # rollup source (best-effort, dump-dir gated like every artifact)
    try:
        from paddle_tpu.core.flags import FLAGS
        if FLAGS.telemetry_dump_dir:
            from paddle_tpu.observability import flight
            flight.dump("weaver")
    except Exception:
        pass


def cmd_list(W):
    print("%-14s %s" % ("scenario", "plants"))
    for name, plants in W.list_scenarios():
        print("%-14s %s" % (name, ", ".join(plants) or "-"))
    return 0


def cmd_replay(W, args):
    reproduced, rec, payload = W.replay_artifact(args.replay)
    out = {
        "artifact": args.replay,
        "scenario": payload.get("scenario"),
        "plant": payload.get("plant"),
        "want_failure": (payload.get("failure") or {}).get("type"),
        "got_failure": rec.failure_type,
        "reproduced": reproduced,
        "decisions": rec.decisions,
    }
    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        print("replay %s: %s (want %s, got %s, %d decisions)"
              % (args.replay,
                 "REPRODUCED" if reproduced else "NOT reproduced",
                 out["want_failure"], out["got_failure"],
                 rec.decisions))
        if rec.failure is not None:
            for s in rec.sites:
                print("  site: %s" % s)
    return 0 if reproduced else 1


def explore_one(W, name, args, results):
    pb = args.preemption_bound
    t0 = time.time()
    stats, failing = W.explore(
        name, plant=args.plant, mode=args.mode,
        max_schedules=args.max_schedules,
        max_decisions=args.max_decisions, seed=args.seed,
        preemption_bound=pb)
    row = {
        "scenario": name,
        "plant": args.plant,
        "mode": args.mode,
        "explored": stats.explored,
        "pruned": stats.pruned,
        "exhausted": stats.exhausted,
        "truncated": stats.truncated,
        "seconds": round(time.time() - t0, 3),
        "failure": failing.failure_type if failing else None,
        "artifact": None,
        "minimized_len": None,
    }
    if failing is not None:
        trace = failing.trace
        if not args.no_minimize:
            trace, _ = W.minimize(name, failing.trace,
                                  failing.failure_type,
                                  plant=args.plant, preemption_bound=pb)
        rec = W.run_schedule(name, trace=trace, plant=args.plant,
                             preemption_bound=pb)
        path = W.write_artifact(_out_dir(args), name, args.plant, trace,
                                rec, stats=stats,
                                minimized_from=len(failing.trace),
                                preemption_bound=pb)
        row["artifact"] = path
        row["minimized_len"] = len(trace)
        row["sites"] = rec.sites
    results.append(row)
    if not args.json:
        status = row["failure"] or (
            "clean (exhausted)" if row["exhausted"] else "clean")
        print("%-14s %-16s %6d explored %6d pruned %6.1fs  %s"
              % (name, args.plant or "-", row["explored"], row["pruned"],
                 row["seconds"], status))
        if row["artifact"]:
            print("  minimized to %d decisions -> %s"
                  % (row["minimized_len"], row["artifact"]))
            for s in row.get("sites", ()):
                print("  site: %s" % s)
    return 1 if row["failure"] else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="systematic concurrency exploration of the "
                    "pserver/fleet/KV-pool protocol scenarios")
    ap.add_argument("--scenario", default="all",
                    help="scenario name or 'all' (see --list)")
    ap.add_argument("--plant", default=None,
                    help="re-introduce a historical race in the "
                         "scenario (see --list for names)")
    ap.add_argument("--mode", choices=("dfs", "random"), default="dfs")
    ap.add_argument("--max-schedules", type=int, default=4000)
    ap.add_argument("--max-decisions", type=int, default=None)
    ap.add_argument("--preemption-bound", default=None,
                    help="max preemptions per schedule (int or 'none'; "
                         "default %d)" % 3)
    ap.add_argument("--seed", type=int, default=0,
                    help="random-walk seed (--mode random)")
    ap.add_argument("--out-dir", default=None,
                    help="artifact directory (default "
                         "FLAGS_telemetry_dump_dir or .)")
    ap.add_argument("--replay", default=None, metavar="ARTIFACT",
                    help="re-execute a weaver_*.json artifact")
    ap.add_argument("--quick", action="store_true",
                    help="budgeted tier-1 smoke: every scenario on "
                         "HEAD, preemption bound 2")
    ap.add_argument("--no-minimize", action="store_true")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import weaver as W

    if args.list:
        return cmd_list(W)
    if args.replay:
        return cmd_replay(W, args)

    if args.preemption_bound is None:
        args.preemption_bound = W.DEFAULT_PREEMPTION_BOUND
    elif str(args.preemption_bound).lower() == "none":
        args.preemption_bound = None
    else:
        args.preemption_bound = int(args.preemption_bound)
    if args.max_decisions is None:
        args.max_decisions = W.DEFAULT_MAX_DECISIONS
    if args.quick:
        # the tier-1 smoke: small bound, capped tree, HEAD only —
        # seconds, not minutes
        args.preemption_bound = min(args.preemption_bound or 2, 2)
        args.max_schedules = min(args.max_schedules, 1200)
        args.plant = None

    if args.scenario == "all":
        names = [n for n, _ in W.list_scenarios()]
        if args.plant:
            names = [n for n in names
                     if args.plant in dict(W.list_scenarios())[n]]
            if not names:
                print("no scenario has plant %r" % args.plant,
                      file=sys.stderr)
                return 2
    else:
        if args.scenario not in W.SCENARIOS:
            print("unknown scenario %r (have: %s)"
                  % (args.scenario, ", ".join(W.SCENARIOS)),
                  file=sys.stderr)
            return 2
        names = [args.scenario]

    results = []
    rc = 0
    for name in names:
        rc |= explore_one(W, name, args, results)
    _dump_metrics()
    if args.json:
        print(json.dumps({"results": results, "rc": rc}, indent=1,
                         sort_keys=True))
    return rc


if __name__ == "__main__":
    sys.exit(main())
