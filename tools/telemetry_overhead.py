#!/usr/bin/env python
"""Tier-1 gate: the instrumented-but-DISABLED executor hot path must
cost < 2% of a prepared step (ISSUE 6 CI satellite; the
tools/lint_program.py-style standalone checker, also run in-process by
tests/test_telemetry.py) — and, since ISSUE 8, so must the numerics
observatory's METRICS mode (health fetch enabled).  Since ISSUE 9 the
serving tier joins the gate: its per-request metric observations
(queue-wait/occupancy/request-latency) must cost < 2% of a
single-request serve, measured as a metrics-on vs metrics-off A/B
through the in-process request plane.  Since ISSUE 11 the generative
decode loop joins too: the per-token metric op set (tokens/TTFT/ITL/
occupancy) must cost < 2% of the measured inter-token latency,
decomposed the same way.

Method for the disabled path — deterministic, not an A/B wall-clock
race (2% of a ~50 µs dispatch loop is far below scheduler noise on
shared CI):

1. measure the prepared hot path as it exists NOW (instrumentation
   compiled in, FLAGS_telemetry off) — min-of-repeats per-step wall on
   a tiny 2-fc program;
2. measure the marginal cost of the disabled-path telemetry operations
   directly: ``trace.disabled_step_probe`` executes exactly the
   per-iteration work an instrumented site adds when tracing is off
   (one ``TRACER.on`` read + one always-on counter inc), timed over
   enough iterations that the per-op figure is stable;
3. overhead_frac = (probe cost x instrumented sites per step) /
   measured step wall.  The pre-instrumentation baseline is therefore
   ``step - overhead`` by construction — the subtraction a historical
   binary could not give us without keeping one around.

The site count is a deliberate over-estimate (every guard counted as a
full probe iteration including the counter inc, though the real path
pays the inc once per step), so the gate is conservative.

Method for metrics mode — a min-of-repeats A/B on a step big enough
that 2% clears scheduler noise (hidden 128 x batch 128: the health
reduction touches ~100k elements against a ~13 MFLOP step): the same
program prepared twice, FLAGS_check_numerics off vs 'metrics' (fused
per-tensor stats as one extra step output + the default read-back
cadence), interleaved repeats, min per arm.

Since ISSUE 14 the sanitizer joins: the FLAGS_sanitizer=off hot path
must be a single module-attribute read per guarded site
(``core/sanitizer.disabled_probe``, decomposed like the telemetry
probe and gated < 2%), and the 'buffers' mode's measured prepared-loop
step is documented in the gate JSON (opt-in debug tier, not gated).

Exit 0 when EVERY gated fraction is < 2% (TELEMETRY_OVERHEAD_MAX /
NUMERICS_OVERHEAD_MAX / ... env overrides); prints one JSON line
either way.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# guard reads + the step-counter inc on one run_prepared: the
# run_prepared wrapper (counter + guard + call), the _impl feed/dispatch
# guards, and slack for future sites — deliberately generous
SITES_PER_STEP = 8


def _measure_step_us(steps=None, repeats=3):
    """Per-step wall of the prepared hot path, telemetry disabled
    (the instrumented binary as shipped).  Min over repeats: the
    stable floor, immune to one-off GC/scheduler stalls."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.observability.trace import TRACER

    steps = steps or int(os.environ.get("TELEMETRY_OVERHEAD_STEPS",
                                        "300"))
    assert not TRACER.on, "run the overhead gate with telemetry off"
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[32], dtype="float32")
        h = fluid.layers.fc(x, size=32, act="relu")
        loss = fluid.layers.mean(fluid.layers.fc(h, size=8))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.ones((8, 32), np.float32)}
    prep = exe.prepare(main, feed_specs=feed, fetch_list=[loss])
    for _ in range(10):   # warm the jit caches
        prep.run_prepared(feed)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            prep.run_prepared(feed)
        best = min(best, (time.perf_counter() - t0) / steps)
    prep.sync_scope()
    return best * 1e6


def _measure_probe_ns(iters=200000, repeats=3):
    """Marginal per-iteration cost of the disabled-path telemetry ops
    (guard read + counter inc)."""
    from paddle_tpu.observability import trace

    trace.disabled_step_probe(1000)   # warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter_ns()
        trace.disabled_step_probe(iters)
        best = min(best, (time.perf_counter_ns() - t0) / iters)
    return best


def _measure_numerics_us(steps=None, repeats=4):
    """Metrics-mode overhead of the ISSUE 8 numerics observatory on
    the prepared path, decomposed deterministically (same philosophy
    as the disabled-path gate above — a plain A/B on this step size is
    below shared-CI scheduler noise):

    In metrics mode the prepared path dispatches its
    health-instrumented twin executable only every
    FLAGS_check_numerics_every steps (the plain executable otherwise),
    so the per-step cost decomposes into

        (health_step - plain_step) / every   amortized stats+decode
      +  monitor python per step             want_health + observe(None)

    The first term is measured as a min-of-repeats A/B where the
    SIGNAL is large (the health step pays one fused reduction pass
    over the watched bytes + the host read-back, ~15% of this step)
    and the division by ``every`` shrinks the noise with it; the
    second term is micro-timed directly, like disabled_step_probe.

    Returns (plain_us, health_us, python_ns): per-plain-step wall,
    per-health-step wall (cadence forced to every step), and monitor
    python ns/step."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.observability import numerics as num

    steps = steps or int(os.environ.get("NUMERICS_OVERHEAD_STEPS",
                                        "160"))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[128], dtype="float32")
            h = fluid.layers.fc(x, size=128, act="relu")
            loss = fluid.layers.mean(fluid.layers.fc(h, size=128))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    scope = fluid.Scope()
    feed = {"x": np.ones((128, 128), np.float32)}
    best = {"plain": float("inf"), "health": float("inf")}
    prev_mode = FLAGS.check_numerics
    prev_every = FLAGS.check_numerics_every
    try:
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            FLAGS.check_numerics = "metrics"
            prep = exe.prepare(main, feed_specs=feed, fetch_list=[loss])
            for _ in range(10):
                prep.run_prepared(feed)
            # 'plain' arm: cadence never fires (first step already
            # consumed) -> every step runs the plain twin + monitor
            # python; 'health' arm: cadence 1 -> every step runs the
            # instrumented twin + decode.  Interleaved min-of-repeats.
            for _ in range(repeats):
                for arm, every in (("plain", 1 << 30), ("health", 1)):
                    FLAGS.check_numerics_every = every
                    for _ in range(3):
                        prep.run_prepared(feed)
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        prep.run_prepared(feed)
                    best[arm] = min(best[arm],
                                    (time.perf_counter() - t0) / steps)
            FLAGS.check_numerics_every = prev_every
            prep.sync_scope()
            # monitor python per step, micro-timed (the 'plain' arm
            # above already contains it; this isolates it for the
            # report and for the amortized-step subtraction)
            mon = num.HealthMonitor(("a", "b"), "probe")
            iters = 20000
            t0 = time.perf_counter_ns()
            for _ in range(iters):
                mon.want_health()
                mon.observe(None)
            python_ns = (time.perf_counter_ns() - t0) / iters
    finally:
        FLAGS.check_numerics = prev_mode
        FLAGS.check_numerics_every = prev_every
    return best["plain"] * 1e6, best["health"] * 1e6, python_ns


def _measure_serving_us(n=None, repeats=3):
    """Metrics-on vs metrics-off single-request latency through the
    serving tier's in-process request plane (ISSUE 9 satellite gate).

    Decomposed like the disabled-path gate above — a wall-clock A/B
    cannot resolve this: the full per-request metric op set costs ~4 µs
    while two thread handoffs put ±80 µs of scheduler noise on a
    ~450 µs request (measured; rep deltas ranged -9..+123 µs).  So:

    1. measure the single-request latency as shipped (metrics ON,
       serial closed loop, max_wait=0 — no coalesce wait), mean over n
       requests, min over repeats;
    2. micro-time ``batcher.metrics_probe`` — the COMPLETE op set
       ``_METRICS_ON`` gates for a request forming its own batch (the
       un-amortized worst case);
    3. the metrics-off latency is then on - probe by construction.

    Returns (on_us, off_us)."""
    import tempfile

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import serving
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.serving import batcher

    n = n or int(os.environ.get("SERVING_OVERHEAD_REQUESTS", "300"))
    d = tempfile.mkdtemp(prefix="serve_gate_")
    main_p, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main_p, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[64],
                                      dtype="float32")
                h = fluid.layers.fc(x, size=256, act="tanh")
                out = fluid.layers.fc(h, size=16, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(
            d, ["x"], [out], exe, main_program=main_p,
            aot_feed_specs={"x": ((1, 64), "float32")})
    feed = {"x": np.ones((1, 64), np.float32)}
    on_us = float("inf")
    with serving.InferenceServer(max_batch=2, max_wait_us=0) as srv:
        srv.load("m", d, warm=[1])
        for _ in range(50):
            srv.predict("m", feed)
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(n):
                srv.predict("m", feed)
            on_us = min(on_us,
                        (time.perf_counter() - t0) / n * 1e6)
    batcher.metrics_probe(1000)   # warm
    probe_us = float("inf")
    iters = 20000
    for _ in range(repeats):
        t0 = time.perf_counter()
        batcher.metrics_probe(iters)
        probe_us = min(probe_us,
                       (time.perf_counter() - t0) / iters * 1e6)
    return on_us, on_us - probe_us


def _measure_generate_us(tokens=None, repeats=3):
    """Decode-loop metrics gate (ISSUE 11 satellite): metrics-on vs
    metrics-off INTER-TOKEN latency through the generative tier,
    decomposed like the serving gate above (the per-token metric op set
    costs single-digit µs against a multi-ms decode iteration — a
    wall-clock A/B is all scheduler noise):

    1. measure the inter-token latency as shipped (metrics ON): one
       generative tenant, single-sequence closed-loop greedy decode,
       mean inter-token gap per run, min over repeats;
    2. micro-time ``generative.token_metrics_probe`` — the COMPLETE
       per-token op set in the single-sequence worst case (per-
       iteration ops not amortized across batch neighbours);
    3. metrics-off latency = on - probe by construction.

    Returns (on_us, off_us)."""
    from paddle_tpu import serving
    from paddle_tpu.serving import generative as gen_mod
    from paddle_tpu.serving import tiny_lm

    n = tokens or int(os.environ.get("GENERATE_OVERHEAD_TOKENS", "96"))
    cfg, params = tiny_lm(5, vocab=64, d_model=64, n_heads=4,
                          n_layers=2, d_ff=128, block_size=16,
                          max_blocks=8, max_batch=2)
    prompt = list(range(8))
    on_us = float("inf")
    with serving.InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=32, warm=False)
        srv.generate("g", prompt, max_new_tokens=8).result(120)  # warm
        for _ in range(repeats):
            res = srv.generate("g", prompt,
                               max_new_tokens=n).result(600)
            itl = res["itl_ms"]
            on_us = min(on_us, 1e3 * sum(itl) / len(itl))
    gen_mod.token_metrics_probe(1000)   # warm
    probe_us = float("inf")
    iters = 20000
    for _ in range(repeats):
        t0 = time.perf_counter()
        gen_mod.token_metrics_probe(iters)
        probe_us = min(probe_us,
                       (time.perf_counter() - t0) / iters * 1e6)
    return on_us, on_us - probe_us


def _measure_spec_probe_us(repeats=3, iters=20000):
    """Speculative-decode metrics gate (ISSUE 19 satellite): one spec
    round adds ``generative.spec_metrics_probe``'s op set (round/
    proposed/accepted counters + the draft/verify µs meters) on top of
    the per-token ops, and every round emits >= 1 token — so the
    per-round probe cost is gated against the measured inter-token
    latency, exactly like token_metrics_probe above."""
    from paddle_tpu.serving import generative as gen_mod

    gen_mod.spec_metrics_probe(1000)    # warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        gen_mod.spec_metrics_probe(iters)
        best = min(best, (time.perf_counter() - t0) / iters * 1e6)
    return best


def _measure_ledger_us(repeats=3, iters=2000):
    """Resource-ledger collector gate (ISSUE 12 satellite): the
    collector wakes every FLAGS_ledger_sample_ms and reads every
    registered probe (O(1) counter reads), so its steady-state cost to
    a training loop is bounded by sample_cost / sample_interval of one
    core — measured deterministically, like the disabled-path gate (a
    wall-clock A/B of a microsecond-scale background thread against a
    multi-ms step is pure scheduler noise):

    1. register the heaviest realistic probe set: a real (unstarted)
       VariableServer with populated bookkeeping + the process
       RPCClient + the fastwire module probe;
    2. micro-time ``ledger.sample_now()`` — one full collector
       iteration (collect, gauge mirror, ring append, watch check);
    3. overhead_frac = sample_us / (FLAGS_ledger_sample_ms * 1000).

    Returns (sample_us, interval_ms)."""
    import numpy as np

    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed.rpc import RPCClient, VariableServer
    from paddle_tpu.observability import ledger

    RPCClient.instance()                 # registers the client probe
    scope = Scope()
    srv = VariableServer(scope, {"g%d" % i: i for i in range(8)},
                         lambda b: None, fanin=4)
    # populate the bookkeeping the probe walks (rounds map is the only
    # non-O(1) read — a handful of live rounds, as under staleness)
    for r in range(4):
        srv._round_seen[r] = 0.0
        srv._round_entries[r] = 2
    srv._pending_bytes = 1 << 20
    srv._pending_entries = 8
    g = np.zeros(1024, np.float32)
    for i in range(4):
        srv._pending["g%d" % i][(0, i)] = g
    ledger.sample_now()                  # warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            ledger.sample_now()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e6, max(1, int(FLAGS.ledger_sample_ms))


def _measure_tsdb_us(repeats=3, iters=300):
    """Watchtower registry-sampler gate (ISSUE 13 satellite): the
    sampler appends one snapshot row of the whole registry every
    FLAGS_tsdb_sample_ms, so its steady-state cost is bounded by
    sample_cost / interval — measured deterministically like the
    ledger gate (micro-time one full ``tsdb.sample_registry`` against
    a real on-disk store, over the registry as populated by the gates
    above: ~100 metrics, the realistic worst case).

    Returns (sample_us, interval_ms)."""
    import shutil
    import tempfile

    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.observability import tsdb

    d = tempfile.mkdtemp(prefix="tsdb_gate_")
    try:
        store = tsdb.TSDB(d)
        tsdb.sample_registry(store)      # warm (sid assignment, meta)
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(iters):
                tsdb.sample_registry(store)
            best = min(best, (time.perf_counter() - t0) / iters)
        store.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return best * 1e6, max(1, int(FLAGS.tsdb_sample_ms))


def _measure_slo_us(repeats=3, iters=200, samples=600):
    """Watchtower SLO-evaluator gate (ISSUE 13 satellite): the
    evaluator scans each spec's fast+slow windows every
    FLAGS_slo_eval_ms, so its cost is bounded by eval_cost /
    interval.  Micro-timed over a realistic store (4 specs incl. a
    .rate objective, ``samples`` points per series — more history
    than a default-retention fast window ever holds).

    Returns (eval_us, interval_ms)."""
    import shutil
    import tempfile

    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.observability import slo as slo_mod
    from paddle_tpu.observability import tsdb

    d = tempfile.mkdtemp(prefix="slo_gate_")
    try:
        store = tsdb.TSDB(d)
        now = time.time()
        for i in range(samples):
            store.append_row(
                {"serve_request_ms.p99": 1.0 + (i % 7),
                 "executor_step_wall_ms.p99": 5.0,
                 "pserver_rounds_applied_total": i,
                 "numerics_nonfinite_total": 0},
                t=now - samples + i)
        specs = slo_mod.load_specs(
            "serve_request_ms.p99<=10,"
            "executor_step_wall_ms.p99<=100,"
            "pserver_rounds_applied_total.rate>=0.5,"
            "numerics_nonfinite_total==0")
        ev = slo_mod.Evaluator(store, specs, dump_alerts=False)
        ev.evaluate(now=now)             # warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(iters):
                ev.evaluate(now=now)
            best = min(best, (time.perf_counter() - t0) / iters)
        store.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return best * 1e6, max(1, int(FLAGS.slo_eval_ms))


SANITIZER_SITES_PER_STEP = 4

# weaver_yield hooks + the make_lock/make_event mode reads a prepared
# step's worth of serving/pserver traffic can cross (queue put/get,
# wire call, apply window) — deliberately generous, like SITES_PER_STEP
WEAVER_SITES_PER_STEP = 6


def _measure_weaver_probe_ns(repeats=3, iters=200000):
    """ISSUE 18: the FLAGS_sanitizer!=weaver cost of a weaver_yield
    site is ONE module-attribute read + branch
    (``core/sanitizer.weaver_probe``, decomposed exactly like
    disabled_probe) — micro-timed, then gated as
    probe x WEAVER_SITES_PER_STEP over the measured prepared step."""
    from paddle_tpu.core import sanitizer as san

    san.weaver_probe(1000)                # warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter_ns()
        san.weaver_probe(iters)
        best = min(best, (time.perf_counter_ns() - t0) / iters)
    return best


def _measure_sanitizer_us(steps=None, repeats=3):
    """Sanitizer gate (ISSUE 14 satellite), decomposed like the
    disabled-telemetry gate:

    1. the OFF path: ``core/sanitizer.disabled_probe`` executes exactly
       the per-site disabled work (one module-attribute read + branch),
       micro-timed; overhead_frac = probe x SANITIZER_SITES_PER_STEP /
       the measured prepared step — this is the gated number (< 2%);
    2. BUFFERS mode: the same tiny prepared loop min-of-repeats A/B
       with FLAGS_sanitizer=off vs buffers (per-step husk bookkeeping:
       one dict comprehension over the donated set + O(1) poison
       skips) — documented in the gate JSON, not gated: it is an
       opt-in debug tier like numerics bisect, just a cheap one.

    Returns (probe_ns, off_step_us, buffers_step_us)."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core import sanitizer as san
    from paddle_tpu.core.flags import FLAGS

    san.disabled_probe(1000)              # warm
    probe_ns = float("inf")
    iters = 200000
    for _ in range(repeats):
        t0 = time.perf_counter_ns()
        san.disabled_probe(iters)
        probe_ns = min(probe_ns,
                       (time.perf_counter_ns() - t0) / iters)

    steps = steps or int(os.environ.get("SANITIZER_OVERHEAD_STEPS",
                                        "200"))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[32], dtype="float32")
            h = fluid.layers.fc(x, size=32, act="relu")
            loss = fluid.layers.mean(fluid.layers.fc(h, size=8))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    scope = fluid.Scope()
    feed = {"x": np.ones((8, 32), np.float32)}
    best = {"off": float("inf"), "buffers": float("inf")}
    prev = FLAGS.sanitizer
    try:
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            prep = exe.prepare(main, feed_specs=feed,
                               fetch_list=[loss])
            for _ in range(10):
                prep.run_prepared(feed)
            for _ in range(repeats):
                for arm in ("off", "buffers"):
                    FLAGS.sanitizer = arm
                    for _ in range(3):
                        prep.run_prepared(feed)
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        prep.run_prepared(feed)
                    best[arm] = min(
                        best[arm],
                        (time.perf_counter() - t0) / steps)
            FLAGS.sanitizer = prev
            prep.sync_scope()
    finally:
        FLAGS.sanitizer = prev
    return probe_ns, best["off"] * 1e6, best["buffers"] * 1e6


RING_SITES_PER_STEP = 4


def _measure_ring_us(steps=None, repeats=3):
    """Ring-attention launch-site gate (ISSUE 15 satellite): the
    ``pallas.ring_attention`` / ``pallas.ring_attention_bwd`` spans
    fire at TRACE time (compile-cache-miss cadence) and their disabled
    cost is the same one-attribute-read probe as every other launch
    site — gated like the executor sites: probe x RING_SITES_PER_STEP
    (fwd + bwd spans with slack) over the measured ring fwd+bwd step.
    Returns the per-step wall (us) of a small ring training step on
    however many host devices exist (the span count per step does not
    depend on the mesh width)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.ring import ring_attention

    steps = steps or int(os.environ.get("RING_OVERHEAD_STEPS", "30"))
    devs = jax.devices("cpu")
    p = 4 if len(devs) >= 4 else len(devs)
    mesh = make_mesh({"sp": p}, devices=devs[:p])
    rng = np.random.RandomState(0)
    # big enough that the step is a representative attention launch
    # (at the tiniest shape the whole fwd+bwd is ~50us of dispatch and
    # the conservative 4-site probe would read as >2% of nothing)
    q, k, v = [jnp.asarray(rng.randn(1, 2, 128 * p, 32)
                           .astype(np.float32)) for _ in range(3)]

    grad = jax.jit(jax.grad(
        lambda q, k, v: (ring_attention(q, k, v, mesh, causal=True)
                         .astype(jnp.float32) ** 2).sum(),
        argnums=(0, 1, 2)))
    jax.block_until_ready(grad(q, k, v))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            r = grad(q, k, v)
        jax.block_until_ready(r)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best * 1e6


def _measure_autoshard_us(repeats=3):
    """Elastic SPMD lowering gate (ISSUE 20): auto_shard's strategy
    search + the ShardingPass annotation walk run at compile-cache-miss
    cadence — apply_placement bumps the program version, so every run
    of the pair rides on (and triggers) an XLA recompile of the
    annotated program.  Gated as search+pass wall over the measured
    compile it amortizes against: the ParallelExecutor's first
    prepared run of the same annotated program on however many host
    devices exist.  Returns (autoshard_us, compile_us)."""
    import numpy as np
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models.transformer import get_model
    from paddle_tpu.parallel import spmd

    devs = jax.devices("cpu")
    p = 4 if len(devs) >= 4 else len(devs)
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss, feeds, _ = get_model(
                    vocab_size=32, seq_len=16, d_model=32, n_head=2,
                    n_layers=2, d_ff=64)
        fluid.Executor(fluid.CPUPlace()).run(startup)
    best = float("inf")
    pl = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        pl = spmd.auto_shard(main, p, cost_model=spmd.CostModel(),
                             batch_size=4)
        spmd.apply_placement(main, pl)
        best = min(best, time.perf_counter() - t0)
    with fluid.scope_guard(scope):
        pe = fluid.ParallelExecutor(
            use_tpu=False, loss_name=loss.name, main_program=main,
            scope=scope, num_devices=p)
        rng = np.random.RandomState(0)
        xs = rng.randint(0, 32, (4, 16)).astype(np.int64)
        ys = np.roll(xs, -1, 1)[:, :, None].astype(np.int64)
        t0 = time.perf_counter()
        pe.run(feed={feeds[0].name: xs, feeds[1].name: ys},
               fetch_list=[loss])
        compile_s = time.perf_counter() - t0
    return best * 1e6, compile_s * 1e6


def record_gate_gauges(out):
    """Mirror every measured gate fraction into the always-on registry
    (gate name -> ``telemetry_gate_<name>`` gauge) and, when a
    Watchtower store is configured (FLAGS_tsdb_dir), sample the
    registry once — so overhead history is retained as durable time
    series instead of living only in this tool's stdout (ISSUE 13
    satellite).  Returns the gauge names written."""
    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.observability import metrics

    names = []
    for key, val in out.items():
        if not key.endswith("_frac"):
            continue
        name = "telemetry_gate_" + key
        metrics.gauge(name, "measured overhead fraction from "
                            "tools/telemetry_overhead.py").set(val)
        names.append(name)
    if FLAGS.tsdb_dir:
        try:
            from paddle_tpu.observability import tsdb
            store = tsdb.default_store()
            if store is not None:
                tsdb.sample_registry(store)
        except Exception:
            pass
    return names


def _default_limit():
    """2% on a real rig; 4% when the whole container has fewer than
    4 cores.  The gated ratios divide a fixed python probe cost by a
    step time — on a 1-core CI rig the step shares its only core with
    the OS and the probe's interpreter overhead, and the shipped 2%
    margin is not holdable even on an untouched tree (measured:
    numerics 3.3%, serving 2.2% at HEAD).  Same rig-honesty rule as
    serve_fleet_bench's scaling gate; the env overrides still win."""
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    return "0.02" if cores >= 4 else "0.04"


def main(argv=None):
    dflt = _default_limit()
    step_us = _measure_step_us()
    probe_ns = _measure_probe_ns()
    overhead_us = probe_ns * SITES_PER_STEP / 1e3
    frac = overhead_us / step_us
    limit = float(os.environ.get("TELEMETRY_OVERHEAD_MAX", dflt))
    plain_us, health_us, mon_ns = _measure_numerics_us()
    from paddle_tpu.core.flags import FLAGS as _F
    every = max(1, int(_F.check_numerics_every))
    num_overhead_us = max(0.0, health_us - plain_us) / every \
        + mon_ns / 1e3
    num_frac = num_overhead_us / plain_us
    num_limit = float(os.environ.get("NUMERICS_OVERHEAD_MAX", dflt))
    serve_on_us, serve_off_us = _measure_serving_us()
    serve_frac = max(0.0, serve_on_us - serve_off_us) / serve_off_us
    serve_limit = float(os.environ.get("SERVING_OVERHEAD_MAX", dflt))
    gen_on_us, gen_off_us = _measure_generate_us()
    gen_frac = max(0.0, gen_on_us - gen_off_us) / gen_off_us
    gen_limit = float(os.environ.get("GENERATE_OVERHEAD_MAX", dflt))
    spec_probe_us = _measure_spec_probe_us()
    spec_frac = spec_probe_us / gen_off_us
    spec_limit = float(os.environ.get("SPEC_OVERHEAD_MAX", dflt))
    ledger_us, ledger_ms = _measure_ledger_us()
    ledger_frac = ledger_us / (ledger_ms * 1e3)
    ledger_limit = float(os.environ.get("LEDGER_OVERHEAD_MAX", dflt))
    tsdb_us, tsdb_ms = _measure_tsdb_us()
    tsdb_frac = tsdb_us / (tsdb_ms * 1e3)
    tsdb_limit = float(os.environ.get("TSDB_OVERHEAD_MAX", dflt))
    slo_us, slo_ms = _measure_slo_us()
    slo_frac = slo_us / (slo_ms * 1e3)
    slo_limit = float(os.environ.get("SLO_OVERHEAD_MAX", dflt))
    san_probe_ns, san_off_us, san_buf_us = _measure_sanitizer_us()
    san_frac = (san_probe_ns * SANITIZER_SITES_PER_STEP / 1e3) \
        / san_off_us
    san_limit = float(os.environ.get("SANITIZER_OVERHEAD_MAX", dflt))
    weaver_probe_ns = _measure_weaver_probe_ns()
    weaver_frac = (weaver_probe_ns * WEAVER_SITES_PER_STEP / 1e3) \
        / san_off_us
    weaver_limit = float(os.environ.get("WEAVER_OVERHEAD_MAX", dflt))
    ring_us = _measure_ring_us()
    ring_frac = (probe_ns * RING_SITES_PER_STEP / 1e3) / ring_us
    ring_limit = float(os.environ.get("RING_OVERHEAD_MAX", dflt))
    autoshard_us, autoshard_compile_us = _measure_autoshard_us()
    autoshard_frac = autoshard_us / autoshard_compile_us
    autoshard_limit = float(os.environ.get("AUTOSHARD_OVERHEAD_MAX",
                                           dflt))
    out = {
        "step_us": round(step_us, 2),
        "probe_ns_per_site": round(probe_ns, 1),
        "sites_per_step": SITES_PER_STEP,
        "overhead_us_per_step": round(overhead_us, 3),
        "overhead_frac": round(frac, 5),
        "limit": limit,
        # ISSUE 8: measured prepared-step overhead of the numerics
        # METRICS mode — amortized health-twin step + monitor python
        # at the default read-back cadence
        "numerics_step_plain_us": round(plain_us, 2),
        "numerics_step_health_us": round(health_us, 2),
        "numerics_every": every,
        "numerics_monitor_ns": round(mon_ns, 1),
        "numerics_overhead_us_per_step": round(num_overhead_us, 3),
        "numerics_overhead_frac": round(num_frac, 5),
        "numerics_limit": num_limit,
        # ISSUE 9: serving-tier request-plane metrics, measured A/B
        "serving_request_on_us": round(serve_on_us, 2),
        "serving_request_off_us": round(serve_off_us, 2),
        "serving_overhead_frac": round(serve_frac, 5),
        "serving_limit": serve_limit,
        # ISSUE 11: generative decode loop — per-token metric op set
        # vs measured inter-token latency
        "generate_itl_on_us": round(gen_on_us, 2),
        "generate_itl_off_us": round(gen_off_us, 2),
        "generate_overhead_frac": round(gen_frac, 5),
        "generate_limit": gen_limit,
        # ISSUE 19: speculative decoding — per-round draft/verify
        # metric op set (spec_metrics_probe) vs the measured inter-
        # token latency; every round emits >= 1 token so per-round is
        # the worst per-token charge
        "spec_probe_us_per_round": round(spec_probe_us, 3),
        "spec_overhead_frac": round(spec_frac, 5),
        "spec_limit": spec_limit,
        # ISSUE 12: resource-ledger collector — one full sampling
        # iteration vs the sampling interval (the collector's
        # steady-state core-steal bound)
        "ledger_sample_us": round(ledger_us, 2),
        "ledger_interval_ms": ledger_ms,
        "ledger_overhead_frac": round(ledger_frac, 6),
        "ledger_limit": ledger_limit,
        # ISSUE 13: Watchtower sampler + SLO evaluator — one full
        # registry sample / SLO evaluation pass vs their sampling
        # intervals (the same steady-state core-steal bound as the
        # ledger collector), decomposed like the other gates
        "tsdb_sample_us": round(tsdb_us, 2),
        "tsdb_interval_ms": tsdb_ms,
        "tsdb_overhead_frac": round(tsdb_frac, 6),
        "tsdb_limit": tsdb_limit,
        "slo_eval_us": round(slo_us, 2),
        "slo_interval_ms": slo_ms,
        "slo_overhead_frac": round(slo_frac, 6),
        "slo_limit": slo_limit,
        # ISSUE 14: sanitizer — the FLAGS_sanitizer=off hot path is
        # ONE module-attribute read per guarded site (gated, like the
        # disabled-telemetry path); buffers mode's measured prepared-
        # loop step is documented for the record (opt-in debug tier)
        "sanitizer_probe_ns_per_site": round(san_probe_ns, 1),
        "sanitizer_sites_per_step": SANITIZER_SITES_PER_STEP,
        "sanitizer_step_off_us": round(san_off_us, 2),
        "sanitizer_step_buffers_us": round(san_buf_us, 2),
        "sanitizer_buffers_frac": round(
            max(0.0, san_buf_us - san_off_us) / san_off_us, 5),
        "sanitizer_overhead_frac": round(san_frac, 6),
        "sanitizer_limit": san_limit,
        # ISSUE 18: weaver scheduling hooks (weaver_yield + the
        # make_lock/make_event mode branch) — off-path is one module-
        # attribute read per site, gated like every sanitizer hook
        "weaver_probe_ns_per_site": round(weaver_probe_ns, 1),
        "weaver_sites_per_step": WEAVER_SITES_PER_STEP,
        "weaver_overhead_frac": round(
            (weaver_probe_ns * WEAVER_SITES_PER_STEP / 1e3)
            / san_off_us, 6),
        "weaver_limit": weaver_limit,
        # ISSUE 15: ring-attention launch-site spans (trace-time, like
        # every Pallas site) — probe x sites over the measured ring
        # fwd+bwd step
        "ring_step_us": round(ring_us, 2),
        "ring_sites_per_step": RING_SITES_PER_STEP,
        "ring_overhead_frac": round(ring_frac, 6),
        "ring_limit": ring_limit,
        # ISSUE 20: auto-sharding search + ShardingPass — runs once
        # per program version (compile-cache-miss cadence, the version
        # bump forces the recompile it rides on), gated against the
        # measured compile wall of the annotated program
        "autoshard_pass_us": round(autoshard_us, 1),
        "autoshard_compile_us": round(autoshard_compile_us, 1),
        "autoshard_overhead_frac": round(autoshard_frac, 6),
        "autoshard_limit": autoshard_limit,
        "ok": (frac < limit and num_frac < num_limit
               and serve_frac < serve_limit
               and gen_frac < gen_limit
               and spec_frac < spec_limit
               and ledger_frac < ledger_limit
               and tsdb_frac < tsdb_limit
               and slo_frac < slo_limit
               and san_frac < san_limit
               and weaver_frac < weaver_limit
               and ring_frac < ring_limit
               and autoshard_frac < autoshard_limit),
    }
    # gate name -> gauge (+ one tsdb sample when FLAGS_tsdb_dir is
    # set): the measured overheads become durable history, not just
    # this line of stdout
    record_gate_gauges(out)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
