#!/usr/bin/env python
"""Tier-1 gate: the instrumented-but-DISABLED executor hot path must
cost < 2% of a prepared step (ISSUE 6 CI satellite; the
tools/lint_program.py-style standalone checker, also run in-process by
tests/test_telemetry.py).

Method — deterministic, not an A/B wall-clock race (2% of a ~50 µs
dispatch loop is far below scheduler noise on shared CI):

1. measure the prepared hot path as it exists NOW (instrumentation
   compiled in, FLAGS_telemetry off) — min-of-repeats per-step wall on
   a tiny 2-fc program;
2. measure the marginal cost of the disabled-path telemetry operations
   directly: ``trace.disabled_step_probe`` executes exactly the
   per-iteration work an instrumented site adds when tracing is off
   (one ``TRACER.on`` read + one always-on counter inc), timed over
   enough iterations that the per-op figure is stable;
3. overhead_frac = (probe cost x instrumented sites per step) /
   measured step wall.  The pre-instrumentation baseline is therefore
   ``step - overhead`` by construction — the subtraction a historical
   binary could not give us without keeping one around.

The site count is a deliberate over-estimate (every guard counted as a
full probe iteration including the counter inc, though the real path
pays the inc once per step), so the gate is conservative.

Exit 0 when overhead_frac < FLAGS-default 2% (TELEMETRY_OVERHEAD_MAX
env overrides); prints one JSON line either way.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# guard reads + the step-counter inc on one run_prepared: the
# run_prepared wrapper (counter + guard + call), the _impl feed/dispatch
# guards, and slack for future sites — deliberately generous
SITES_PER_STEP = 8


def _measure_step_us(steps=None, repeats=3):
    """Per-step wall of the prepared hot path, telemetry disabled
    (the instrumented binary as shipped).  Min over repeats: the
    stable floor, immune to one-off GC/scheduler stalls."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.observability.trace import TRACER

    steps = steps or int(os.environ.get("TELEMETRY_OVERHEAD_STEPS",
                                        "300"))
    assert not TRACER.on, "run the overhead gate with telemetry off"
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[32], dtype="float32")
        h = fluid.layers.fc(x, size=32, act="relu")
        loss = fluid.layers.mean(fluid.layers.fc(h, size=8))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.ones((8, 32), np.float32)}
    prep = exe.prepare(main, feed_specs=feed, fetch_list=[loss])
    for _ in range(10):   # warm the jit caches
        prep.run_prepared(feed)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            prep.run_prepared(feed)
        best = min(best, (time.perf_counter() - t0) / steps)
    prep.sync_scope()
    return best * 1e6


def _measure_probe_ns(iters=200000, repeats=3):
    """Marginal per-iteration cost of the disabled-path telemetry ops
    (guard read + counter inc)."""
    from paddle_tpu.observability import trace

    trace.disabled_step_probe(1000)   # warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter_ns()
        trace.disabled_step_probe(iters)
        best = min(best, (time.perf_counter_ns() - t0) / iters)
    return best


def main(argv=None):
    step_us = _measure_step_us()
    probe_ns = _measure_probe_ns()
    overhead_us = probe_ns * SITES_PER_STEP / 1e3
    frac = overhead_us / step_us
    limit = float(os.environ.get("TELEMETRY_OVERHEAD_MAX", "0.02"))
    out = {
        "step_us": round(step_us, 2),
        "probe_ns_per_site": round(probe_ns, 1),
        "sites_per_step": SITES_PER_STEP,
        "overhead_us_per_step": round(overhead_us, 3),
        "overhead_frac": round(frac, 5),
        "limit": limit,
        "ok": frac < limit,
    }
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
