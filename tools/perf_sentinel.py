#!/usr/bin/env python
"""Perf-regression sentinel (ISSUE 13 tentpole c): turn the pile of
bench artifacts into ONE trajectory, and fail loudly when a run
regresses.

Every measured claim this repo makes lives in a disconnected JSON file
— ``SERVE_BENCH.json``, ``PSERVER_BENCH.json``, ``SCALE_BENCH.json``,
``LONGCTX_BENCH.json``, the driver-wrapped ``BENCH_r*.json`` training
runs — and nothing compares across them.  This tool:

1. **ingests** every known artifact under ``--repo`` (plus optional
   Watchtower tsdb stores via ``--tsdb``) through per-shape extractors
   into named scalar metrics with an explicit better-direction,
2. **builds** ``PERF_TRAJECTORY.json``: metric -> ordered runs ->
   recorded floor (the best non-quick value ever measured) — the
   canonical perf record every later run is judged against
   (MIGRATION.md),
3. **checks** (``--check RUN.json``): extracts the same metrics from a
   fresh run and exits **rc 3** when any regresses more than
   ``--max-regress`` (default 15%) against its recorded floor.  Quick
   (smoke-sized) runs only ever compare against quick floors — a
   seconds-scale CI smoke is not evidence against a full run's floor.

Wired as ``--sentinel`` at the end of tools/serve_bench.py,
tools/pserver_bench.py and tools/scale_bench.py (ROADMAP: bench tools
should always pass it), and smoke-tested in tier-1
(tests/test_watchtower.py: a synthetic trajectory with a planted
regression must rc 3; clean must rc 0).

Usage:
    python tools/perf_sentinel.py                      # build + write
    python tools/perf_sentinel.py --check NEW.json     # gate a run
    python tools/perf_sentinel.py --tsdb /tmp/tsdb --json
"""
import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TRAJECTORY_NAME = "PERF_TRAJECTORY.json"
DEFAULT_MAX_REGRESS = 0.15
RC_REGRESSION = 3


def _m(value, hib=True, unit=""):
    if value is None:
        return None
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return {"value": v, "higher_is_better": bool(hib), "unit": unit}


def _get(obj, *path):
    for key in path:
        if not isinstance(obj, dict):
            return None
        obj = obj.get(key)
    return obj


def _extract_serve(obj):
    out = {
        "serve_floor_qps": _m(_get(obj, "floor", "qps"), True, "qps"),
        "serve_poisson_qps": _m(_get(obj, "poisson", "qps"), True,
                                "qps"),
        "serve_poisson_p99_ms": _m(_get(obj, "poisson", "p99_ms"),
                                   False, "ms"),
        "serve_saturated_qps": _m(_get(obj, "saturated", "qps"), True,
                                  "qps"),
        "serve_gen_floor_tokens_s": _m(
            _get(obj, "generate", "floor", "tokens_s"), True, "tok/s"),
        "serve_gen_poisson_tokens_s": _m(
            _get(obj, "generate", "poisson", "tokens_s"), True,
            "tok/s"),
        "serve_gen_itl_p99_ms": _m(
            _get(obj, "generate", "poisson", "itl_p99_ms"), False,
            "ms"),
        # ISSUE 19: speculative decoding — solo tok/s, acceptance, and
        # the speedup over the same engine decoding plainly
        "serve_spec_tokens_s": _m(
            _get(obj, "spec", "spec", "tokens_s"), True, "tok/s"),
        "serve_spec_accept_rate": _m(
            _get(obj, "spec", "spec", "accept_rate"), True, "frac"),
        "serve_spec_speedup_x": _m(
            _get(obj, "spec", "speedup_vs_plain"), True, "x"),
    }
    # ISSUE 19: shared-prefix phase — gate the HARDEST mix (the last,
    # 95% shared): cached-prefill TTFT and the FLOPs the radix index
    # avoided
    mixes = _get(obj, "prefix", "mixes") or []
    if mixes:
        last = mixes[-1]
        out["serve_prefix_ttft_p50_ms"] = _m(
            _get(last, "ttft_p50_ms", "on"), False, "ms")
        out["serve_prefix_flops_avoided_pct"] = _m(
            last.get("prefill_flops_avoided_pct"), True, "%")
    return {k: v for k, v in out.items() if v is not None}


def _extract_pserver(obj):
    out = {
        "pserver_dense_rounds_per_sec": _m(
            obj.get("dense_rounds_per_sec"), True, "rounds/s"),
        "pserver_sparse_rows_per_sec": _m(
            obj.get("sparse_rows_per_sec"), True, "rows/s"),
        "pserver_ctr_flat_rows_per_sec": _m(
            _get(obj, "ctr", "flat_sync", "rows_per_sec"), True,
            "rows/s"),
        "pserver_ctr_hier_async_rows_per_sec": _m(
            _get(obj, "ctr", "hier_async_int8", "rows_per_sec"), True,
            "rows/s"),
    }
    return {k: v for k, v in out.items() if v is not None}


def _extract_longctx(obj):
    """tools/longctx_bench.py (ISSUE 15): per sequence length the ring
    tokens/s (higher better) and peak RSS (lower better), plus the
    64k ring-vs-baseline ratio when the baseline survived to be
    measured."""
    out = {}
    for pt in obj.get("points") or []:
        seq = pt.get("seq")
        ring = pt.get("ring") or {}
        if not seq or ring.get("collapsed"):
            continue
        if ring.get("tokens_s"):
            out["longctx_ring_tokens_s_%dk" % (seq // 1024)] = _m(
                ring["tokens_s"], True, "tok/s")
        if ring.get("peak_rss_mb"):
            out["longctx_ring_peak_rss_mb_%dk" % (seq // 1024)] = _m(
                ring["peak_rss_mb"], False, "MB")
        if pt.get("ring_vs_baseline"):
            out["longctx_ring_vs_baseline_%dk" % (seq // 1024)] = _m(
                pt["ring_vs_baseline"], True, "x")
    return {k: v for k, v in out.items() if v is not None}


def _extract_fleet(obj):
    """tools/serve_fleet_bench.py (ISSUE 16): solo-process floor,
    aggregate fleet tok/s under open-loop Poisson, the scaling
    multiple itself, and the kill drill's TTFT recovery (lower
    better — how fast the router heals after a SIGKILL)."""
    out = {
        "fleet_gen_floor_tokens_s": _m(
            _get(obj, "floor", "tokens_s"), True, "tok/s"),
        "fleet_poisson_tokens_s": _m(
            _get(obj, "scale", "tokens_s") if not obj.get("quick")
            else _get(obj, "poisson", "tokens_s"), True, "tok/s"),
        "fleet_scaling_x": _m(_get(obj, "scale", "scaling_x"), True,
                              "x"),
        "fleet_kill_ttft_recovery_s": _m(
            _get(obj, "kill", "ttft_recovery_s"), False, "s"),
    }
    return {k: v for k, v in out.items() if v is not None}


def _extract_scale(obj):
    rows = [r.get("rows_per_sec")
            for r in (obj.get("sweep") or []) + (obj.get("variants")
                                                 or [])
            if isinstance(r, dict) and r.get("rows_per_sec")]
    out = {}
    if rows:
        out["scale_peak_rows_per_sec"] = _m(max(rows), True, "rows/s")
    knee = _get(obj, "knee", "trainers")
    if knee:
        out["scale_knee_trainers"] = _m(knee, True, "trainers")
    return out


def _extract_autoshard(obj):
    """tools/autoshard_bench.py (ISSUE 20): ratio metrics only — raw
    CPU step ms flakes across runs, but auto-vs-best-hand gap
    fractions (clamped at 1.0), the reshard parity boolean, and the
    measured-strategy count are machine-stable."""
    out = {}
    for p, rec in sorted((obj.get("per_p") or {}).items()):
        gap = rec.get("auto_gap_frac")
        if gap is not None:
            out["autoshard_gap_p%s" % p] = _m(gap, False, "frac")
    n = sum(len(rec.get("strategies") or [])
            for rec in (obj.get("per_p") or {}).values())
    if n:
        out["autoshard_strategies_measured"] = _m(n, True, "legs")
    reshard = obj.get("reshard") or {}
    if "parity_ok" in reshard:
        out["autoshard_parity_ok"] = _m(
            1.0 if reshard["parity_ok"] else 0.0, True, "bool")
    return out


def _extract_bench_lines(text):
    """The driver-wrapped training bench (BENCH_r*.json 'tail'): each
    measured claim is one ``{"metric": ..., "value": ..., "unit"}``
    JSON line on stdout; the 'partial' headline is superseded by the
    enriched exit line of the same metric when both landed."""
    found = {}
    for line in str(text).splitlines():
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue
        name, value = d.get("metric"), d.get("value")
        if not name or value is None:
            continue
        unit = d.get("unit") or ""
        hib = not ("ms" in unit or unit.endswith("_s")
                   or "latency" in str(name))
        if name in found and d.get("partial") \
                and not found[name].get("_partial"):
            continue
        m = _m(value, hib, unit)
        if m is None:       # non-numeric value: not a metric line
            continue
        found[name] = dict(m, _partial=bool(d.get("partial")))
        sec = d.get("secondary")
        if isinstance(sec, dict) and sec.get("metric") \
                and sec.get("value") is not None:
            found[sec["metric"]] = dict(
                _m(sec["value"], True, sec.get("unit") or "") or {})
    return {k: {kk: vv for kk, vv in v.items() if kk != "_partial"}
            for k, v in found.items() if v}


def extract_metrics(obj):
    """Route one artifact (parsed JSON) to its extractor; returns
    ({metric: {value, higher_is_better, unit}}, quick_flag)."""
    if isinstance(obj, dict) and "tail" in obj and "cmd" in obj:
        return _extract_bench_lines(obj.get("tail", "")), False
    kind = obj.get("metric") if isinstance(obj, dict) else None
    quick = bool(isinstance(obj, dict) and obj.get("quick"))
    if kind == "serve_bench":
        return _extract_serve(obj), quick
    if kind == "pserver_bench":
        return _extract_pserver(obj), quick
    if kind == "scale_bench":
        return _extract_scale(obj), quick
    if kind == "longctx_bench":
        return _extract_longctx(obj), quick
    if kind == "serve_fleet_bench":
        return _extract_fleet(obj), quick
    if kind == "autoshard_bench":
        return _extract_autoshard(obj), quick
    if isinstance(obj, dict) and kind and "value" in obj:
        # a bare bench.py headline line saved to a file
        return _extract_bench_lines(json.dumps(obj)), quick
    return {}, quick


def load_artifact(path):
    with open(path) as f:
        return json.load(f)


def collect_repo(repo):
    """[(source, metrics, quick)] over every known artifact, in a
    deterministic order (numbered training rounds first — they are
    the oldest evidence — then the current per-subsystem records)."""
    runs = []
    paths = sorted(glob.glob(os.path.join(repo, "BENCH_r*.json")))
    for name in ("PSERVER_BENCH.json", "SERVE_BENCH.json",
                 "SCALE_BENCH.json", "LONGCTX_BENCH.json",
                 "SERVE_FLEET_BENCH.json", "AUTOSHARD_BENCH.json"):
        p = os.path.join(repo, name)
        if os.path.exists(p):
            paths.append(p)
    for p in paths:
        try:
            metrics, quick = extract_metrics(load_artifact(p))
        except Exception:
            continue
        if metrics:
            runs.append((os.path.basename(p), metrics, quick))
    return runs


def ingest_tsdb(root):
    """Summarize every per-process Watchtower store under ``root``:
    {store: {series: {last, mean, max, n}}} — durable evidence rows
    for the trajectory (context, not gated)."""
    from paddle_tpu.observability import tsdb as _tsdb

    out = {}
    for label, store in sorted(_tsdb.open_stores(root).items()):
        rows = {}
        for name in store.names():
            t, v = store.scan(name)
            if len(v) == 0:
                continue
            rows[name] = {"last": round(float(v[-1]), 6),
                          "mean": round(float(v.mean()), 6),
                          "max": round(float(v.max()), 6),
                          "n": int(len(v))}
        if rows:
            out[label] = rows
    return out


def build_trajectory(repo=None, tsdb_root=None, runs=None):
    """metric -> ordered runs -> floor.  The floor is the best
    FULL-run value ever recorded (max for higher-is-better, min
    otherwise); quick smoke runs track their own quick_floor so a CI
    smoke is only ever judged against smoke-sized evidence."""
    runs = collect_repo(repo or REPO) if runs is None else runs
    metrics = {}
    for source, mdict, quick in runs:
        for name, m in mdict.items():
            ent = metrics.setdefault(name, {
                "unit": m.get("unit", ""),
                "higher_is_better": m["higher_is_better"],
                "runs": []})
            ent["runs"].append({"source": source,
                                "value": m["value"],
                                "quick": bool(quick)})
    for name, ent in metrics.items():
        pick = max if ent["higher_is_better"] else min
        for key, want_quick in (("floor", False), ("quick_floor",
                                                   True)):
            vals = [r["value"] for r in ent["runs"]
                    if r["quick"] == want_quick]
            ent[key] = pick(vals) if vals else None
        ent["latest"] = ent["runs"][-1]["value"]
    traj = {"kind": "perf_trajectory", "version": 1,
            "built_from": [s for s, _, _ in runs],
            "metrics": metrics}
    if tsdb_root:
        try:
            traj["tsdb"] = ingest_tsdb(tsdb_root)
        except Exception as e:
            traj["tsdb_error"] = str(e)[:200]
    return traj


def check_metrics(traj, mdict, quick=False,
                  max_regress=DEFAULT_MAX_REGRESS):
    """Compare one run's metrics against the trajectory floors.
    Returns (regressions, checked, skipped) — a regression row names
    the metric, the floor, the new value and the fraction lost."""
    regressions, checked, skipped = [], [], []
    floor_key = "quick_floor" if quick else "floor"
    for name, m in sorted(mdict.items()):
        ent = (traj.get("metrics") or {}).get(name)
        floor = ent.get(floor_key) if ent else None
        if ent is None or floor is None or floor == 0:
            skipped.append(name)
            continue
        v = m["value"]
        if ent["higher_is_better"]:
            regress = (floor - v) / abs(floor)
        else:
            regress = (v - floor) / abs(floor)
        row = {"metric": name, "floor": floor, "value": v,
               "regress_frac": round(regress, 4),
               "higher_is_better": ent["higher_is_better"],
               "quick": bool(quick)}
        checked.append(row)
        if regress > max_regress:
            regressions.append(row)
    return regressions, checked, skipped


def check_artifact(path_or_obj, traj=None, repo=None,
                   max_regress=DEFAULT_MAX_REGRESS):
    """The --sentinel entry the bench tools call: extract the fresh
    run, compare against the recorded trajectory (built from the repo
    when none is passed), return (rc, report_dict)."""
    obj = load_artifact(path_or_obj) \
        if isinstance(path_or_obj, str) else path_or_obj
    if traj is None:
        traj_path = os.path.join(repo or REPO, TRAJECTORY_NAME)
        traj = load_artifact(traj_path) \
            if os.path.exists(traj_path) \
            else build_trajectory(repo or REPO)
    mdict, quick = extract_metrics(obj)
    regressions, checked, skipped = check_metrics(
        traj, mdict, quick=quick, max_regress=max_regress)
    report = {"kind": "perf_sentinel_check",
              "max_regress": max_regress, "quick": bool(quick),
              "checked": checked, "skipped": skipped,
              "regressions": regressions,
              "ok": not regressions}
    return (0 if not regressions else RC_REGRESSION), report


def sentinel_gate(out):
    """The ONE --sentinel implementation the bench tools share:
    check the fresh run dict against the recorded trajectory, print
    a one-line JSON report, return the rc (0 clean, 3 regression).
    Report shape / rc policy live here, not in three copies."""
    rc, report = check_artifact(out)
    print(json.dumps({"sentinel": {
        "ok": report["ok"], "checked": len(report["checked"]),
        "skipped": len(report["skipped"]),
        "regressions": report["regressions"]}}))
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="build PERF_TRAJECTORY.json from every bench "
                    "artifact; gate new runs against recorded floors")
    ap.add_argument("--repo", default=REPO,
                    help="repo root to scan for *_BENCH.json / "
                         "BENCH_r*.json artifacts")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="trajectory output path (default "
                         "<repo>/%s)" % TRAJECTORY_NAME)
    ap.add_argument("--no-write", action="store_true",
                    help="build/report only; leave the trajectory "
                         "file untouched")
    ap.add_argument("--tsdb", default=None, metavar="DIR",
                    help="Watchtower tsdb root to ingest (per-process "
                         "store summaries ride the trajectory)")
    ap.add_argument("--check", default=None, metavar="RUN.json",
                    help="gate this fresh run artifact against the "
                         "recorded floors; rc 3 on regression")
    ap.add_argument("--max-regress", type=float,
                    default=DEFAULT_MAX_REGRESS,
                    help="tolerated loss vs the recorded floor "
                         "(fraction, default 0.15)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full JSON instead of the table")
    args = ap.parse_args(argv)

    traj = build_trajectory(args.repo, tsdb_root=args.tsdb)
    out_path = args.out or os.path.join(args.repo, TRAJECTORY_NAME)
    if not args.no_write:
        from paddle_tpu.core.fsutil import atomic_write
        atomic_write(out_path, json.dumps(traj, indent=1,
                                          sort_keys=True) + "\n")

    if args.check:
        rc, report = check_artifact(args.check, traj=traj,
                                    max_regress=args.max_regress)
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            for row in report["checked"]:
                mark = "REGRESSED" if row in report["regressions"] \
                    else "ok"
                print("%-40s floor %12.4g  new %12.4g  %+7.1f%%  %s"
                      % (row["metric"], row["floor"], row["value"],
                         -100.0 * row["regress_frac"], mark))
            for name in report["skipped"]:
                print("%-40s (no recorded %sfloor — skipped)"
                      % (name,
                         "quick " if report["quick"] else ""))
            print("sentinel: %d checked, %d skipped, %d regression(s)"
                  % (len(report["checked"]), len(report["skipped"]),
                     len(report["regressions"])))
        return rc

    if args.json:
        print(json.dumps(traj, indent=2, sort_keys=True))
    else:
        print("%-40s %7s %12s %12s %12s" % (
            "metric", "runs", "floor", "latest", "direction"))
        for name, ent in sorted(traj["metrics"].items()):
            print("%-40s %7d %12.4g %12.4g %12s" % (
                name, len(ent["runs"]),
                ent["floor"] if ent["floor"] is not None
                else float("nan"),
                ent["latest"],
                "higher" if ent["higher_is_better"] else "lower"))
        if not args.no_write:
            print("wrote %s (%d metrics from %d artifacts)"
                  % (out_path, len(traj["metrics"]),
                     len(traj["built_from"])))
    return 0


if __name__ == "__main__":
    sys.exit(main())
