#!/usr/bin/env python
"""Generate Kubernetes manifests for distributed training jobs.

Parity: reference benchmark/fluid/kube_gen_job.py + kube_templates/
(pserver ReplicaSet + trainer Job carrying the PADDLE_* env contract).
TPU-native deltas:

- trainer pods request ``google.com/tpu`` resources instead of GPUs and
  mesh over their chips via ParallelExecutor (no per-GPU pod fanout);
- ``--disttype nccl2`` emits the jax.distributed contract
  (PADDLE_TRAINER_ENDPOINTS via a headless service + pod index);
- ``--discovery-root`` mounts a shared volume and sets
  PADDLE_DISCOVERY_ROOT so pservers/master register dynamically
  (distributed/discovery.py) instead of baking static IPs.

Emits plain JSON manifests (a strict YAML subset — kubectl accepts
them), no external yaml dependency.
"""
from __future__ import annotations

import argparse
import json
import sys


def base_env(args):
    return [
        {"name": "PADDLE_PSERVER_PORT", "value": str(args.port)},
        {"name": "PADDLE_TRAINERS", "value": str(args.trainers)},
        {"name": "JOB_NAME", "value": args.jobname},
    ]


def _pod(name, image, cmd, env, resources, labels,
         restart_policy="Always", subdomain=None):
    spec = {
        # ReplicaSet templates only allow Always; the trainer Job
        # overrides with Never
        "restartPolicy": restart_policy,
        "containers": None,  # filled below
    }
    if subdomain:
        spec["subdomain"] = subdomain
    return {
        "metadata": {"labels": dict(labels)},
        "spec": dict(spec, containers=[{
                "name": name,
                "image": image,
                "command": ["sh", "-c", cmd],
                "env": list(env),
                "resources": resources,
            }]),
    }


def gen_pserver(args):
    env = base_env(args) + [
        {"name": "PADDLE_TRAINING_ROLE", "value": "PSERVER"},
        {"name": "PADDLE_CURRENT_IP",
         "valueFrom": {"fieldRef": {"fieldPath": "status.podIP"}}},
    ]
    if args.discovery_root:
        env += [{"name": "PADDLE_DISCOVERY_ROOT",
                 "value": args.discovery_root},
                {"name": "PADDLE_PSERVERS_EXPECTED",
                 "value": str(args.pservers)}]
    else:
        env.append({"name": "PADDLE_PSERVER_IPS",
                    "value": args.pserver_ips})
    res = {"requests": {"cpu": str(args.pscpu),
                        "memory": "%dGi" % args.psmemory}}
    labels = {"paddle-job-pserver": args.jobname}
    return {
        "apiVersion": "apps/v1",
        "kind": "ReplicaSet",
        "metadata": {"name": args.jobname + "-pserver"},
        "spec": {
            "replicas": args.pservers,
            "selector": {"matchLabels": labels},
            "template": _pod("pserver", args.image, args.entry, env, res,
                             labels),
        },
    }


def gen_trainer(args):
    env = base_env(args) + [
        {"name": "PADDLE_TRAINING_ROLE", "value": "TRAINER"},
        {"name": "PADDLE_TRAINER_ID", "valueFrom": {"fieldRef": {
            "fieldPath":
                "metadata.annotations['batch.kubernetes.io/"
                "job-completion-index']"}}},
    ]
    if args.disttype == "nccl2":
        # jax.distributed bootstrap: pod 0 of the headless service is
        # the coordinator (distributed/collective.py env contract)
        eps = ",".join(
            "%s-trainer-%d.%s-trainer:%d"
            % (args.jobname, i, args.jobname, args.port + 1)
            for i in range(args.trainers))
        env.append({"name": "PADDLE_TRAINER_ENDPOINTS", "value": eps})
    if args.discovery_root:
        env += [{"name": "PADDLE_DISCOVERY_ROOT",
                 "value": args.discovery_root},
                {"name": "PADDLE_PSERVERS_EXPECTED",
                 "value": str(args.pservers)}]
    elif args.disttype == "pserver":
        env.append({"name": "PADDLE_PSERVER_IPS",
                    "value": args.pserver_ips})
    res = {"requests": {"cpu": str(args.cpu),
                        "memory": "%dGi" % args.memory}}
    if args.tpu:
        res["limits"] = {"google.com/tpu": str(args.tpu)}
    labels = {"paddle-job": args.jobname}
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": args.jobname + "-trainer"},
        "spec": {
            "completions": args.trainers,
            "parallelism": args.trainers,
            "completionMode": "Indexed",
            # Indexed Jobs get stable per-pod hostnames; with the
            # headless Service below + subdomain, pod DNS names like
            # <job>-trainer-0.<job>-trainer resolve (nccl2 coordinator)
            "template": _pod("trainer", args.image, args.entry, env, res,
                             labels, restart_policy="Never",
                             subdomain=args.jobname + "-trainer"
                             if args.disttype == "nccl2" else None),
        },
    }


def gen_trainer_service(args):
    """Headless Service backing the trainers' per-pod DNS (required for
    the nccl2 PADDLE_TRAINER_ENDPOINTS names to resolve)."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": args.jobname + "-trainer"},
        "spec": {
            "clusterIP": "None",
            "selector": {"paddle-job": args.jobname},
            "ports": [{"port": args.port + 1,
                       "targetPort": args.port + 1}],
        },
    }


def gen_master(args):
    env = base_env(args)
    if args.discovery_root:
        env.append({"name": "PADDLE_DISCOVERY_ROOT",
                    "value": args.discovery_root})
    labels = {"paddle-job-master": args.jobname}
    return {
        "apiVersion": "apps/v1",
        "kind": "ReplicaSet",
        "metadata": {"name": args.jobname + "-master"},
        "spec": {
            # active + standby: MasterHA leader election picks one
            "replicas": 2,
            "selector": {"matchLabels": labels},
            "template": _pod("master", args.image, args.master_entry,
                             env, {"requests": {"cpu": "1"}}, labels),
        },
    }


def build(args):
    out = []
    if args.disttype == "pserver":
        out.append(gen_pserver(args))
    if args.disttype == "nccl2":
        out.append(gen_trainer_service(args))
    out.append(gen_trainer(args))
    if args.master:
        out.append(gen_master(args))
    return out


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="Generate dist job manifests (reference "
                    "kube_gen_job.py).")
    p.add_argument("--jobname", default="paddlejob")
    p.add_argument("--image", default="paddle-tpu:latest")
    p.add_argument("--entry", default="python train.py")
    p.add_argument("--master-entry",
                   default="python -m paddle_tpu.distributed.master")
    p.add_argument("--pservers", type=int, default=1)
    p.add_argument("--trainers", type=int, default=1)
    p.add_argument("--cpu", type=int, default=1)
    p.add_argument("--pscpu", type=int, default=1)
    p.add_argument("--memory", type=int, default=1)
    p.add_argument("--psmemory", type=int, default=1)
    p.add_argument("--tpu", type=int, default=0,
                   help="TPU chips per trainer pod")
    p.add_argument("--port", type=int, default=30236)
    p.add_argument("--disttype", default="pserver",
                   choices=["pserver", "nccl2", "local"])
    p.add_argument("--pserver-ips", default="")
    p.add_argument("--discovery-root", default="")
    p.add_argument("--master", action="store_true",
                   help="also emit the HA master ReplicaSet")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    for doc in build(args):
        json.dump(doc, sys.stdout, indent=2)
        sys.stdout.write("\n---\n")


if __name__ == "__main__":
    main()
