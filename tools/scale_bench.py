#!/usr/bin/env python
"""Scale observatory (ISSUE 12): the 64-256-trainer stress lab.

Every distributed number in this repo was measured at 2x2 on
localhost; the protocol, though, is designed for hundreds of trainers.
This harness finds where it actually collapses BEFORE production does:

- **Process-multiplexed trainers.**  N *simulated* trainers — lean
  protocol clients speaking the real wire ((round, sender, seq)
  identities, batched SendVariables frames, durable barriers, batched
  gathers, SendComplete) — are multiplexed as threads over a few
  worker processes and driven against REAL pservers (full transpiled
  listen_and_serv programs, the same VariableServer the training path
  uses).  The workers never import jax: 256 trainers cost 8 light
  processes, not 256 heavyweight ones.
- **Sweep.**  trainers x staleness k x codec x hier-depth (hier-depth
  L is simulated as fan-in reduction: the pserver sees trainers/L
  group leaders, exactly what hierarchical aggregation presents to the
  data plane).  Each point reports aggregate rows/s, barrier-latency
  p50/p99, the pserver's resource-ledger PEAKS (pending-grad bytes,
  reply-cache bytes, barrier set, apply backlog — observability/
  ledger.py), and the quorum-bookkeeping work per round.
- **Knee detection.**  ``detect_knee`` flags the first sweep point
  whose marginal throughput per added trainer drops below a fraction
  of the baseline per-trainer throughput.
- **Collapse forensics** (``--collapse pending``): one straggler + a
  k>0 window drives per-(round, sender) pending-state growth on the
  pserver; ``FLAGS_ledger_watch`` trips a flight-recorder dump whose
  embedded ledger series is the forensic artifact (asserted by the
  tools/fault_matrix.py 'scale' preset).
- **Before/after** (``--before-after``): re-runs a sweep subset with
  the legacy O(trainers)-per-ack barrier rescan + unbounded caches
  (FLAGS_barrier_rescan=1, cache caps 0) against the incremental
  quorum + bounded caches, charting quorum scan ops/round and ledger
  peaks — the measured proof for the ISSUE 12 collapse fix.

Run:  python tools/scale_bench.py --json SCALE_BENCH.json
      python tools/scale_bench.py --quick          # CI tier-1 smoke
"""
import argparse
import glob
import json
import multiprocessing as mp
import os
import shutil
import sys
import tempfile
import threading
import time

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=2")
os.environ["JAX_PLATFORMS"] = "cpu"   # host-path benchmark, like pserver_bench

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np

# dense model dims (grad = DIM_IN x DIM_OUT f32).  Env-overridable:
# spawned children re-import this module and re-derive them.
DIM_IN = int(os.environ.get("SCB_DIM_IN", "512"))
DIM_OUT = int(os.environ.get("SCB_DIM_OUT", "128"))
# nominal minibatch rows one simulated trainer round represents — the
# rows/s numerator (a sync round ships one batch's grads per trainer)
ROWS_PER_ROUND = int(os.environ.get("SCB_ROWS", "64"))
N_PSERVERS = int(os.environ.get("SCB_PSERVERS", "2"))
WORKER_PROCS = int(os.environ.get("SCB_PROCS", "8"))
STRAGGLE_S = float(os.environ.get("SCB_STRAGGLE_S", "0.4"))

KNEE_FRAC = float(os.environ.get("SCB_KNEE_FRAC", "0.5"))


# ---------------------------------------------------------------------------
# knee detection (unit-tested by tests/test_scale_ledger.py)
# ---------------------------------------------------------------------------

def detect_knee(points, frac=KNEE_FRAC):
    """``points``: [(n_trainers, aggregate_throughput)], sorted by n.
    The knee is the FIRST sweep point whose marginal throughput per
    added trainer, (thr[i]-thr[i-1])/(n[i]-n[i-1]), drops below
    ``frac`` x the baseline per-trainer throughput (thr[0]/n[0]) —
    i.e. where adding trainers stops buying proportional throughput.
    Returns {"trainers", "marginal_per_trainer", "base_per_trainer",
    "threshold_frac"} or None when the curve never bends."""
    pts = sorted((int(n), float(t)) for n, t in points)
    if len(pts) < 2 or pts[0][0] <= 0:
        return None
    base = pts[0][1] / pts[0][0]
    if base <= 0:
        return None
    for (n0, t0), (n1, t1) in zip(pts, pts[1:]):
        marginal = (t1 - t0) / max(1, n1 - n0)
        if marginal < frac * base:
            return {"trainers": n1,
                    "marginal_per_trainer": round(marginal, 3),
                    "base_per_trainer": round(base, 3),
                    "threshold_frac": frac}
    return None


# ---------------------------------------------------------------------------
# pserver child: the REAL transpiled serve loop + a ledger-peaks report
# ---------------------------------------------------------------------------

def _build_model():
    import paddle_tpu.fluid as fluid

    zinit = fluid.initializer.ConstantInitializer(0.0)
    x = fluid.layers.data(name="x", shape=[DIM_IN], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(
        input=x, size=DIM_OUT,
        param_attr=fluid.ParamAttr(name="big_w", initializer=zinit),
        bias_attr=False)
    pred = fluid.layers.fc(
        input=h, size=1,
        param_attr=fluid.ParamAttr(name="head_w", initializer=zinit),
        bias_attr=False)
    loss = fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return loss


def _transpile(pservers, n_senders):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                _build_model()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers=pservers, trainers=n_senders, sync_mode=True)
    return t, scope


def trainer_routes(pservers, n_senders):
    """[(ep, grad_block_name, param_block_name, shape)] — the wire
    routing the transpiler stamped into the trainer's send/recv ops,
    extracted so the simulated trainers can speak it without carrying
    the whole fluid stack."""
    t, _scope = _transpile(pservers, n_senders)
    routes = []
    for p, g in t.params_grads:
        for blk in t.param_blocks[p]:
            routes.append((t.block_ep[blk.name],
                           t._grad_block_name(g, blk), blk.name,
                           [int(d) for d in blk.shape]))
    return routes


def run_pserver(endpoint, pservers, n_senders, env, ledger_out):
    for k, v in (env or {}).items():
        os.environ[k] = v
    import paddle_tpu.fluid as fluid
    from paddle_tpu.observability import ledger as obs_ledger
    from paddle_tpu.observability import metrics as obs_metrics

    t, scope = _transpile(pservers, n_senders)
    ps_prog = t.get_pserver_program(endpoint)
    ps_startup = t.get_startup_program(endpoint, ps_prog)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(ps_startup)
        exe.run(ps_prog)          # serves until every sender completes
    # final sample + peaks over the whole run: the per-sweep-point
    # resource curve the parent charts against trainer count
    try:
        obs_ledger.sample_now()
    except Exception:
        pass
    snap = obs_metrics.snapshot()

    def _val(name):
        return (snap.get(name) or {}).get("value", 0)

    rec = {
        "endpoint": endpoint,
        "ledger_peaks": obs_ledger.peaks(),
        "rounds_applied": _val("pserver_rounds_applied_total"),
        "quorum_scan_ops": _val("pserver_quorum_scan_ops_total"),
        "reply_cache_evictions": _val(
            "pserver_reply_cache_evictions_total"),
        "dedup_drops": _val("pserver_dedup_drops_total"),
    }
    with open(ledger_out, "w") as f:
        json.dump(rec, f)


# ---------------------------------------------------------------------------
# worker child: a few processes, many simulated-trainer threads, NO jax
# ---------------------------------------------------------------------------

class SimTrainer:
    """One simulated trainer: the real wire protocol over a shared
    per-process gRPC channel set.  Grad payloads are generated once
    and re-sent each round under fresh (round, sender, seq)
    identities — the pserver's bookkeeping (pending maps, dedup,
    quorum, reply cache) does exactly the work a real trainer causes;
    only the local SGD compute is elided."""

    def __init__(self, sender_id, routes, channels, codec, timeout):
        from paddle_tpu.distributed import compress as czip

        self.sender = 0x0A0000 + sender_id
        self.label = "sim%04d" % sender_id
        self.timeout = timeout
        self.channels = channels
        self._seq = 0
        rng = np.random.RandomState(1234 + sender_id)
        self.by_ep = {}
        for ep, gname, pname, shape in routes:
            arr = rng.rand(*shape).astype(np.float32)
            if codec:
                # pre-encode once; the same post-codec frame re-sends
                # every round (real trainers re-encode per round, but
                # the pserver-side decode + bookkeeping — the stress
                # target — is identical)
                arr = czip.compress(arr, codec)
            self.by_ep.setdefault(ep, []).append((gname, pname, arr))
        self.round_s = []
        self.barrier_s = []
        # wall-clock bounds of the TIMED rounds (time.time: comparable
        # across worker processes, unlike perf_counter) — round 0 is a
        # warm-up (channel connect, first-apply jit) and must not
        # dilute the throughput denominator
        self.t_start = self.t_end = 0.0

    def _call(self, ep, method, payload):
        fn = self.channels[ep].unary_unary(
            "/paddle_tpu.PServer/%s" % method)
        return fn(payload, wait_for_ready=True, timeout=self.timeout)

    def _next_seq(self):
        self._seq = (self._seq % ((1 << 14) - 1)) + 1
        return self._seq

    def run(self, rounds, straggle_s=0.0):
        from paddle_tpu.distributed.rpc import (
            _enc_batch_parts, _enc_msg, _enc_tensor_parts, _join_parts,
            _pack_round_sender)

        eps = sorted(self.by_ep)
        for r in range(rounds + 1):       # +1: round 0 is the warm-up
            if r == 1:
                self.t_start = time.time()
            t_round = time.perf_counter()
            if straggle_s and r > 0:
                time.sleep(straggle_s)
            for ep in eps:
                frames = [
                    _enc_tensor_parts(
                        gname, arr,
                        _pack_round_sender(r, self.sender,
                                           self._next_seq()))
                    for gname, _p, arr in self.by_ep[ep]]
                self._call(ep, "SendVariables",
                           _join_parts(_enc_batch_parts(frames)))
            t_bar = time.perf_counter()
            for ep in eps:     # same ep order on every sender: safe
                self._call(ep, "SendBarrier",
                           _enc_msg(self.label,
                                    _pack_round_sender(r, self.sender)))
            t_ack = time.perf_counter()
            for ep in eps:
                gets = [[_enc_msg(pname, r + 1)]
                        for _g, pname, _a in self.by_ep[ep]]
                self._call(ep, "GetVariables",
                           _join_parts(_enc_batch_parts(gets)))
            if r > 0:
                self.round_s.append(time.perf_counter() - t_round)
                self.barrier_s.append(t_ack - t_bar)
                self.t_end = time.time()

    def complete(self):
        from paddle_tpu.distributed.rpc import _enc_msg, \
            _pack_round_sender

        for ep in sorted(self.by_ep):
            try:
                self._call(ep, "SendComplete",
                           _enc_msg(self.label,
                                    _pack_round_sender(0, self.sender)))
            except Exception:
                pass


def run_workers(sender_ids, routes, rounds, straggler_ids, codec,
                timeout, queue, env):
    for k, v in (env or {}).items():
        os.environ[k] = v
    import grpc

    eps = sorted({r[0] for r in routes})
    channels = {ep: grpc.insecure_channel(
        ep, options=[("grpc.max_send_message_length", -1),
                     ("grpc.max_receive_message_length", -1)])
        for ep in eps}
    trainers = [SimTrainer(sid, routes, channels, codec, timeout)
                for sid in sender_ids]
    errs = {}

    def one(tr, sid):
        try:
            tr.run(rounds,
                   straggle_s=STRAGGLE_S if sid in straggler_ids else 0)
        except Exception as e:
            errs[sid] = "%s: %s" % (type(e).__name__, str(e)[:200])

    ts = [threading.Thread(target=one, args=(tr, sid))
          for tr, sid in zip(trainers, sender_ids)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for tr in trainers:
        tr.complete()
    queue.put({
        "senders": len(trainers),
        # timed-round wall bounds only (warm-up excluded); the parent
        # takes min(start)/max(end) ACROSS workers — time.time is the
        # one clock comparable between processes on this host
        "t_start": min((tr.t_start for tr in trainers
                        if tr.t_start), default=0.0),
        "t_end": max(tr.t_end for tr in trainers),
        "round_s": [s for tr in trainers for s in tr.round_s],
        "barrier_s": [s for tr in trainers for s in tr.barrier_s],
        "errors": errs,
    })


# ---------------------------------------------------------------------------
# one sweep point
# ---------------------------------------------------------------------------

def _pctl(vals, p):
    # the ONE nearest-rank definition (observability/metrics.py) —
    # scale_bench's p99 must agree with trace_report's for the same
    # data.  Parent-process only; the jax-free workers never need it.
    from paddle_tpu.observability.metrics import nearest_rank

    return nearest_rank(sorted(vals), p)


def run_point(trainers, base_port, rounds, staleness=0, codec="",
              hier=1, extra_env=None, straggler_ids=(), dump_dir=None,
              timeout=None):
    """One (trainers, k, codec, hier) run; returns the sweep row."""
    senders = trainers // max(1, hier)
    if senders < 1:
        raise ValueError("hier=%d leaves no senders for trainers=%d"
                         % (hier, trainers))
    timeout = timeout or max(120.0, rounds * 20.0)
    ctx = mp.get_context("spawn")
    eps = ["127.0.0.1:%d" % (base_port + i) for i in range(N_PSERVERS)]
    pservers = ",".join(eps)
    own_dump = dump_dir is None
    if own_dump:
        dump_dir = tempfile.mkdtemp(prefix="scale_bench_")
    env = {
        "FLAGS_dist_staleness": str(staleness),
        "FLAGS_ledger_sample_ms": os.environ.get(
            "SCB_LEDGER_MS", "50"),
        "FLAGS_telemetry_dump_dir": dump_dir,
        "SCB_DIM_IN": str(DIM_IN), "SCB_DIM_OUT": str(DIM_OUT),
        # sim clients pre-encode frames; trainer-side codec flags are
        # irrelevant to the children but the pserver decodes kind-2
        # frames unconditionally
    }
    env.update(extra_env or {})
    ledger_files = [os.path.join(dump_dir, "ledger_ps%d.json" % i)
                    for i in range(N_PSERVERS)]
    ps_procs = [ctx.Process(target=run_pserver,
                            args=(ep, pservers, senders, env, lf))
                for ep, lf in zip(eps, ledger_files)]
    results, wk_procs = [], []
    try:
        for p in ps_procs:
            p.start()
        time.sleep(2.0)
        routes = trainer_routes(pservers, senders)
        q = ctx.Queue()
        n_procs = max(1, min(senders, WORKER_PROCS))
        chunks = [list(range(senders))[i::n_procs]
                  for i in range(n_procs)]
        wk_procs = [ctx.Process(
            target=run_workers,
            args=(chunk, routes, rounds, tuple(straggler_ids), codec,
                  timeout, q, env))
            for chunk in chunks if chunk]
        for p in wk_procs:
            p.start()
        results = [q.get(timeout=timeout + 120) for _ in wk_procs]
        for p in wk_procs + ps_procs:
            p.join(timeout=120)
    finally:
        for p in wk_procs + ps_procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)
    starts = [r["t_start"] for r in results if r["t_start"]]
    wall = (max(r["t_end"] for r in results) - min(starts)) \
        if starts else 0.0
    barrier_ms = [1e3 * s for r in results for s in r["barrier_s"]]
    errors = {}
    for r in results:
        errors.update(r["errors"])
    # merge pserver ledger reports: peak = max across shards, work
    # counters summed
    peaks, scans, applied = {}, 0, 0
    for lf in ledger_files:
        try:
            with open(lf) as f:
                rec = json.load(f)
        except Exception:
            continue
        for k, v in rec.get("ledger_peaks", {}).items():
            peaks[k] = max(peaks.get(k, 0), v)
        scans += rec.get("quorum_scan_ops", 0)
        applied += rec.get("rounds_applied", 0)
    rps = rounds / wall if wall > 0 else 0.0
    row = {
        "trainers": trainers, "hier": hier, "senders": senders,
        "staleness": staleness, "codec": codec or "raw",
        "rounds": rounds,
        "rounds_per_sec": round(rps, 3),
        "rows_per_sec": int(rps * trainers * ROWS_PER_ROUND),
        "round_ms_p50": round(
            _pctl([1e3 * s for r in results for s in r["round_s"]], 50),
            1),
        "barrier_ms_p50": round(_pctl(barrier_ms, 50), 1),
        "barrier_ms_p99": round(_pctl(barrier_ms, 99), 1),
        "ledger_peaks": peaks,
        "quorum_scan_ops_per_round": int(scans / applied)
        if applied else 0,
    }
    if errors:
        row["errors"] = dict(list(errors.items())[:4])
    if own_dump:
        shutil.rmtree(dump_dir, ignore_errors=True)
    return row


# ---------------------------------------------------------------------------
# collapse forensics
# ---------------------------------------------------------------------------

def run_collapse(mode, trainers, base_port, rounds):
    """Drive one collapse mode and return {mode, tripped,
    flight_artifacts, ...}: a straggler under a k>0 window grows the
    pserver's per-(round, sender) pending state; FLAGS_ledger_watch
    turns the crossing into a flight dump whose embedded ledger series
    is the forensic evidence."""
    assert mode == "pending", "collapse modes: pending"
    grad_bytes = DIM_IN * DIM_OUT * 4
    k = 3
    # threshold: ~1.5 fast rounds' worth of pending grads per shard —
    # crossed only when the fast senders run ahead of the straggler
    thr = int(0.75 * (trainers - 1) * grad_bytes)
    dump_dir = tempfile.mkdtemp(prefix="scale_collapse_")
    row = run_point(
        trainers, base_port, rounds, staleness=k,
        extra_env={
            "FLAGS_ledger_watch":
                "pserver_pending_grad_bytes>%d" % thr,
            "FLAGS_ledger_sample_ms": "20",
        },
        straggler_ids=(0,), dump_dir=dump_dir)
    arts = sorted(glob.glob(os.path.join(dump_dir, "flight_*.json")))
    evidence = []
    for path in arts:
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        led = rec.get("ledger") or {}
        series = led.get("series") or []
        if not series:
            continue
        peak = max((s["values"].get("pserver_pending_grad_bytes", 0)
                    for s in series), default=0)
        evidence.append({
            "path": path, "reason": rec.get("reason"),
            "ledger_samples": len(series),
            "peak_pending_grad_bytes": peak,
        })
    return {
        "mode": mode, "trainers": trainers, "staleness": k,
        "straggler_delay_s": STRAGGLE_S,
        "watch_threshold_bytes": thr,
        "tripped": bool(evidence),
        "flight_artifacts": evidence,
        "dump_dir": dump_dir,
        "rounds_per_sec": row["rounds_per_sec"],
        "ledger_peaks": row["ledger_peaks"],
    }


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main(argv=None):
    ap = argparse.ArgumentParser(
        description="scale observatory: N simulated trainers vs real "
                    "pservers, resource-ledger curves, knee detection")
    ap.add_argument("--quick", action="store_true",
                    help="tiny dims, 4+8 trainers, 3 rounds: a "
                         "seconds-scale smoke (CI tier-1)")
    ap.add_argument("--json", metavar="PATH", default=None)
    ap.add_argument("--trainers", default=None,
                    help="comma-separated sweep counts "
                         "(default 8,16,32,64,128,256)")
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--collapse", choices=["pending"], default=None,
                    help="drive one collapse mode and collect the "
                         "ledger flight artifact")
    ap.add_argument("--before-after", action="store_true",
                    help="re-run a sweep subset with the legacy "
                         "O(trainers) barrier rescan + unbounded "
                         "caches vs the fixed path")
    ap.add_argument("--no-sweep", action="store_true",
                    help="skip the trainer-count sweep (e.g. with "
                         "--collapse only)")
    ap.add_argument("--no-variants", action="store_true",
                    help="skip the staleness/codec/hier variants")
    ap.add_argument("--sentinel", action="store_true",
                    help="gate this run against PERF_TRAJECTORY.json "
                         "via tools/perf_sentinel.py (rc 3 on a >15%% "
                         "regression vs the recorded floor; quick "
                         "runs only compare against quick floors).  "
                         "ROADMAP: always pass this")
    args = ap.parse_args(argv)

    global DIM_IN, DIM_OUT
    if args.quick:
        os.environ.setdefault("SCB_DIM_IN", "128")
        os.environ.setdefault("SCB_DIM_OUT", "32")
        DIM_IN = int(os.environ["SCB_DIM_IN"])
        DIM_OUT = int(os.environ["SCB_DIM_OUT"])
        counts = [4, 8]
        rounds = args.rounds or 3
    else:
        counts = [8, 16, 32, 64, 128, 256]
        rounds = args.rounds or 6
    if args.trainers:
        counts = [int(c) for c in args.trainers.split(",")]

    out = {
        "metric": "scale_bench",
        "quick": bool(args.quick),
        "pservers": N_PSERVERS,
        "worker_procs": WORKER_PROCS,
        "grad_bytes_per_trainer_round": DIM_IN * DIM_OUT * 4,
        "rows_per_trainer_round": ROWS_PER_ROUND,
        "knee_threshold_frac": KNEE_FRAC,
    }
    port = 21310
    if not args.no_sweep:
        sweep = []
        for n in counts:
            try:
                sweep.append(run_point(n, port, rounds))
            except Exception as e:
                sweep.append({"trainers": n,
                              "error": str(e)[:200]})
            port += 40
            # emit-immediately discipline (bench.py): partial results
            # survive a wall-budget kill of a later, bigger point
            out["sweep"] = sweep
            out["knee"] = detect_knee(
                [(r["trainers"], r["rows_per_sec"])
                 for r in sweep if "rows_per_sec" in r])
            print(json.dumps({"partial": True, "sweep": sweep[-1]}),
                  flush=True)
    if not args.no_variants and not args.no_sweep:
        base_n = min(64, max(counts))
        variants = []
        for label, kw in (
                ("staleness_k2", {"staleness": 2}),
                ("int8", {"codec": "int8"}),
                ("hier_4", {"hier": 4}),
                ("hier4_k2_int8", {"staleness": 2, "codec": "int8",
                                   "hier": 4})):
            if base_n // kw.get("hier", 1) < 1:
                continue
            try:
                row = run_point(base_n, port, rounds, **kw)
                row["variant"] = label
                variants.append(row)
            except Exception as e:
                variants.append({"variant": label,
                                 "error": str(e)[:200]})
            port += 40
        out["variants"] = variants
    if args.before_after:
        legacy_env = {"FLAGS_barrier_rescan": "1",
                      "FLAGS_pserver_reply_cache_mb": "0",
                      "FLAGS_rpc_replay_cache_mb": "0"}
        subset = [c for c in counts if c <= 64] or counts[:3]
        ba = {"legacy": [], "fixed": []}
        for arm, env in (("legacy", legacy_env), ("fixed", {})):
            for n in subset:
                try:
                    ba[arm].append(run_point(n, port, rounds,
                                             extra_env=env))
                except Exception as e:
                    ba[arm].append({"trainers": n,
                                    "error": str(e)[:200]})
                port += 40
        for arm in ("legacy", "fixed"):
            ba["knee_" + arm] = detect_knee(
                [(r["trainers"], r["rows_per_sec"])
                 for r in ba[arm] if "rows_per_sec" in r])
        out["before_after"] = ba
    if args.collapse:
        out["collapse"] = run_collapse(
            args.collapse, 8 if args.quick else 16, port, rounds)

    line = json.dumps(out)
    print(line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")
    # a requested collapse that left no ledger-bearing artifact is a
    # failure — the fault_matrix 'scale' preset keys off this rc
    if args.collapse and not out["collapse"]["tripped"]:
        return 2
    if args.sentinel:
        # perf sentinel (ISSUE 13): rc 3 when a measured metric
        # regresses >15% against its recorded PERF_TRAJECTORY floor
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from perf_sentinel import sentinel_gate

        return sentinel_gate(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
