"""Pserver throughput microbenchmark (round-3 VERDICT weak #3;
planet-scale sparse tier in ISSUE 10).

The reference's C++ ParameterServer2 (paddle/pserver/ParameterServer2.h)
was a performance component: sharded updates, zero-copy sockets.  Its
replacement here is the fastwire pserver (distributed/rpc.py) behind
the distribute transpiler.  This tool measures what that pserver
actually sustains on localhost, end to end through the REAL training
path (transpiled programs, 2 trainers, sync mode):

  dense  — one ~100 MB fc parameter: full grad up + param down every
           round; reports rounds/sec and the aggregate wire MB/s the
           server moved.  A compression sweep re-runs it per
           FLAGS_dist_compress codec and reports wire bytes/round +
           the effective compression ratio from the wire counters.
  sparse — a 1M-row x 64 embedding with is_sparse=True: per-step
           SelectedRows updates; reports touched rows/sec.
  ctr    — the production-recommender shape (ISSUE 10): a
           multi-ten-million-row DISTRIBUTED embedding
           (distributed_lookup prefetch, table never leaves the
           pservers) under power-law (zipf) row access, measured twice
           — flat sync, and scaled with hierarchical aggregation +
           bounded-staleness async + int8/rows compression.

Run:  python tools/pserver_bench.py  (writes one JSON line to stdout)

The JSON includes `fraction_of_chip_step`: with the measured round
time, the share of a 100 ms accelerator step (the ResNet-50 headline's
step time) a synchronous round would consume if overlapped 1:1 — the
"can this pserver feed one chip" statement the VERDICT asked for.
"""
import json
import multiprocessing as mp
import os
import sys
import time

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=2")
# FORCE cpu (not setdefault): the pserver bench is a host-path benchmark
# by definition; a rig-exported JAX_PLATFORMS must not pull in a (maybe
# dead) accelerator tunnel
os.environ["JAX_PLATFORMS"] = "cpu"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np

# dense: 4096 x 6400 f32 = 104.9 MB parameter.  Env-overridable (not
# argv): spawn children re-import this module fresh, so the quick-mode
# dims must travel through the environment to reach them.
DENSE_IN = int(os.environ.get("PSB_DENSE_IN", "4096"))
DENSE_OUT = int(os.environ.get("PSB_DENSE_OUT", "6400"))
DENSE_BATCH = 8
# sparse: 1M x 64 embedding, 1024 samples x 4 ids per step
VOCAB = int(os.environ.get("PSB_VOCAB", "1000000"))
EMB_DIM = 64
SPARSE_BATCH = int(os.environ.get("PSB_SPARSE_BATCH", "1024"))
IDS_PER_SAMPLE = 4
# ctr: 20M x 16 sharded table, 32k samples x 16 ids, zipf row access
# (hash-feature dims are narrow in production CTR; batch sized so the
# ~570k distinct rows a step touches amortize the round's fixed costs)
CTR_VOCAB = int(os.environ.get("PSB_CTR_VOCAB", "20000000"))
CTR_DIM = int(os.environ.get("PSB_CTR_DIM", "16"))
CTR_BATCH = int(os.environ.get("PSB_CTR_BATCH", "32768"))
CTR_IDS = int(os.environ.get("PSB_CTR_IDS", "16"))
CTR_ZIPF = float(os.environ.get("PSB_CTR_ZIPF", "1.05"))


def build_model(kind):
    import paddle_tpu.fluid as fluid

    zinit = fluid.initializer.ConstantInitializer(0.0)
    if kind in ("sparse", "ctr"):
        vocab = VOCAB if kind == "sparse" else CTR_VOCAB
        dim = EMB_DIM if kind == "sparse" else CTR_DIM
        ids_n = IDS_PER_SAMPLE if kind == "sparse" else CTR_IDS
        ids = fluid.layers.data(name="ids", shape=[ids_n],
                                dtype="int64")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        # distributed lookup table (the DeepFM-style workload SURVEY
        # §2.5 keeps the pserver path FOR): trainers prefetch only the
        # batch's rows and push SelectedRows updates — no full-table
        # sync per round.  The ctr shape never materializes the table
        # off the pservers at all (2.6 GB f32 at the default dims).
        emb = fluid.layers.embedding(
            ids, size=[vocab, dim], is_sparse=True,
            is_distributed=True,
            param_attr=fluid.ParamAttr(
                name="emb_w",
                initializer=fluid.initializer.ConstantInitializer(0.02)))
        pooled = fluid.layers.reduce_mean(emb, dim=1)
        pred = fluid.layers.fc(
            input=pooled, size=1,
            param_attr=fluid.ParamAttr(name="fc_w",
                                       initializer=zinit),
            bias_attr=fluid.ParamAttr(name="fc_b", initializer=zinit))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
    else:
        x = fluid.layers.data(name="x", shape=[DENSE_IN],
                              dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(
            input=x, size=DENSE_OUT,
            param_attr=fluid.ParamAttr(name="big_w", initializer=zinit),
            bias_attr=False)
        pred = fluid.layers.fc(
            input=h, size=1,
            param_attr=fluid.ParamAttr(name="head_w",
                                       initializer=zinit),
            bias_attr=False)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return loss


def make_batch(step, kind, trainer_id=0):
    rng = np.random.RandomState(1000 * step + trainer_id)
    if kind == "ctr":
        # power-law (zipf) row access: the head ids dominate like real
        # CTR traffic, the tail still sweeps the multi-ten-million-row
        # table
        ids = ((rng.zipf(CTR_ZIPF, (CTR_BATCH, CTR_IDS)) - 1)
               % CTR_VOCAB).astype(np.int64)
        return {"ids": ids,
                "y": rng.rand(CTR_BATCH, 1).astype(np.float32)}
    if kind == "sparse":
        return {
            "ids": rng.randint(0, VOCAB,
                               (SPARSE_BATCH, IDS_PER_SAMPLE)
                               ).astype(np.int64),
            "y": rng.rand(SPARSE_BATCH, 1).astype(np.float32),
        }
    return {
        "x": rng.rand(DENSE_BATCH, DENSE_IN).astype(np.float32),
        "y": rng.rand(DENSE_BATCH, 1).astype(np.float32),
    }


def distinct_rows_per_step(kind, steps, n_trainers=2):
    """Mean count of DISTINCT table rows the trainers touch per step —
    the numerator of rows/s (batches are deterministic per (step,
    trainer), so the parent recomputes them exactly)."""
    counts = []
    for s in range(1, steps + 1):
        ids = np.concatenate([
            make_batch(s, kind, t)["ids"].reshape(-1)
            for t in range(n_trainers)])
        counts.append(len(np.unique(ids)))
    return float(np.mean(counts))


def _apply_env(env):
    if env:
        os.environ.update(env)


def _transpile(trainer_id, pservers, trainers, kind):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = build_model(kind)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=trainer_id, program=main,
                startup_program=startup, pservers=pservers,
                trainers=trainers, sync_mode=True)
    return t, main, startup, scope, loss


def run_pserver(endpoint, pservers, trainers, kind, env=None):
    _apply_env(env)
    import paddle_tpu.fluid as fluid

    t, main, startup, scope, loss = _transpile(0, pservers, trainers,
                                               kind)
    ps_prog = t.get_pserver_program(endpoint)
    ps_startup = t.get_startup_program(endpoint, ps_prog)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(ps_startup)
        exe.run(ps_prog)


def run_trainer(trainer_id, pservers, trainers, steps, queue, kind,
                env=None):
    _apply_env(env)
    # hierarchy leader election + telemetry labels key off the id
    os.environ["PADDLE_TRAINER_ID"] = str(trainer_id)
    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed.rpc import RPCClient
    from paddle_tpu.observability import metrics as obs_metrics

    t, main, startup, scope, loss = _transpile(trainer_id, pservers,
                                               trainers, kind)
    exe = fluid.Executor(fluid.CPUPlace())
    # feeds are pre-generated OUTSIDE the timed loop: zipf rejection
    # sampling costs ~45 ms per 16k x 16 batch — bench harness cost,
    # not data-plane throughput
    feeds = [make_batch(s, kind, trainer_id) for s in range(steps + 1)]
    with fluid.scope_guard(scope):
        exe.run(startup)
        prog = t.get_trainer_program()
        exe.run(prog, feed=feeds[0], fetch_list=[loss])  # warm/compile
        t0 = time.time()
        for s in range(1, steps + 1):
            exe.run(prog, feed=feeds[s], fetch_list=[loss])
        dt = time.time() - t0
    RPCClient.instance().send_complete(t.pserver_endpoints)
    snap = obs_metrics.snapshot()

    def _val(name):
        return (snap.get(name) or {}).get("value", 0)

    queue.put((trainer_id, dt, steps, {
        "wire_bytes_raw_total": _val("wire_bytes_raw_total"),
        "wire_bytes_compressed_total": _val(
            "wire_bytes_compressed_total"),
        "rpc_bytes_sent_total": _val("rpc_bytes_sent_total"),
        "rpc_bytes_recv_total": _val("rpc_bytes_recv_total"),
    }))


def bench(kind, steps, n_pservers=2, n_trainers=2, base_port=19310,
          env=None):
    """One 2x2 run; returns (rounds_per_sec, per-trainer metric dicts).
    ``env`` is exported into every child — the FLAGS_dist_* knobs
    (compress/staleness/hier) travel this way."""
    ctx = mp.get_context("spawn")
    eps = ["127.0.0.1:%d" % (base_port + i) for i in range(n_pservers)]
    pservers = ",".join(eps)
    ps_procs = [ctx.Process(target=run_pserver,
                            args=(ep, pservers, n_trainers, kind, env))
                for ep in eps]
    tr_procs = []
    try:
        for p in ps_procs:
            p.start()
        time.sleep(2.0)
        q = ctx.Queue()
        tr_procs = [ctx.Process(target=run_trainer,
                                args=(i, pservers, n_trainers, steps, q,
                                      kind, env))
                    for i in range(n_trainers)]
        for p in tr_procs:
            p.start()
        results = [q.get(timeout=900) for _ in tr_procs]
        for p in tr_procs + ps_procs:
            p.join(timeout=120)
    finally:
        # a crashed child must not leave non-daemon orphans holding the
        # ports (and blocking interpreter exit)
        for p in tr_procs + ps_procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)
    dt = max(r[1] for r in results)  # rounds complete at the slowest
    return steps / dt, [r[3] for r in results]


def compress_sweep(steps, base_port):
    """Re-run the dense bench per codec and report rounds/s, wire
    bytes/round, and the effective compression ratio straight from the
    trainers' wire counters (warmup round included in the divisor)."""
    out = {}
    for i, mode in enumerate(("", "fp16", "int8", "topk")):
        env = {"FLAGS_dist_compress": mode}
        rps, mets = bench("dense", steps, base_port=base_port + 40 * i,
                          env=env)
        raw = sum(m["wire_bytes_raw_total"] for m in mets)
        comp = sum(m["wire_bytes_compressed_total"] for m in mets)
        rounds = (steps + 1) * len(mets)   # +1: the warmup round
        out[mode or "raw"] = {
            "rounds_per_sec": round(rps, 2),
            "grad_bytes_per_round": int(comp / rounds),
            "compression_ratio": round(raw / comp, 2) if comp else 1.0,
        }
    return out


def ctr_bench(steps, base_port):
    """The CTR-shaped scenario, flat vs scaled (hierarchical
    aggregation + bounded-staleness async + int8/rows compression).
    Quick-mode sizing arrives via the PSB_CTR_* env knobs, like every
    other scenario."""
    distinct = distinct_rows_per_step("ctr", max(3, steps))
    out = {"vocab": CTR_VOCAB, "emb_dim": CTR_DIM,
           "batch": CTR_BATCH, "ids_per_sample": CTR_IDS,
           "zipf_a": CTR_ZIPF,
           "distinct_rows_per_step": int(distinct)}
    runs = [("flat_sync", {})]
    scaled_env = {"FLAGS_dist_compress": "int8",
                  "FLAGS_dist_staleness": "2",
                  "FLAGS_dist_hier_local": "2",
                  "FLAGS_dist_hier_port": str(base_port + 700)}
    runs.append(("hier_async_int8", scaled_env))
    for i, (name, env) in enumerate(runs):
        rps, mets = bench("ctr", steps, base_port=base_port + 40 * i,
                          env=env)
        raw = sum(m["wire_bytes_raw_total"] for m in mets)
        comp = sum(m["wire_bytes_compressed_total"] for m in mets)
        out[name] = {
            "steps_per_sec": round(rps, 2),
            "rows_per_sec": int(rps * distinct),
            "compression_ratio": round(raw / comp, 2) if comp else 1.0,
            "staleness": int(env.get("FLAGS_dist_staleness", "0")),
            "hier_local": int(env.get("FLAGS_dist_hier_local", "0")),
        }
    return out


def component_floor():
    """Measure the round's component floors on THIS machine: the
    fastwire echo (wire both ways), the batched frame encode+decode,
    and the server's aggregate+SGD — so the headline number comes with
    its decomposition instead of a guess."""
    from paddle_tpu.distributed import fastwire
    from paddle_tpu.distributed.rpc import (_dec_tensor,
                                            _enc_tensor_parts,
                                            _iter_batch, _enc_batch_parts,
                                            _aligned_empty)

    floor = {}
    param = np.ones((DENSE_IN, DENSE_OUT), np.float32)
    mb = param.nbytes / 1e6

    # batched frame encode (parts, no join) + zero-copy decode over a
    # received-style buffer.  The join below happens OUTSIDE the timer:
    # the wire never pays it (vectored send / recv-into-one-buffer) —
    # this floor is the actual per-round framing overhead
    parts = _enc_batch_parts([_enc_tensor_parts("w", param)])
    joined = b"".join(bytes(p) if isinstance(p, bytes) else p.tobytes()
                      for p in parts)
    view = memoryview(joined)
    t0 = time.perf_counter()
    _enc_batch_parts([_enc_tensor_parts("w", param)])
    for frame in _iter_batch(view):
        _dec_tensor(frame)
    floor["enc_dec_%dmb_s" % round(mb)] = round(
        time.perf_counter() - t0, 4)

    # codec floor: int8 encode+decode of the same dense param — the
    # per-round cost compression adds before the wire saves 4x
    from paddle_tpu.distributed import compress as czip
    t0 = time.perf_counter()
    c = czip.compress(param, "int8")
    czip.decompress(c)
    floor["int8_codec_%dmb_s" % round(mb)] = round(
        time.perf_counter() - t0, 4)

    if fastwire.native_available():
        import socket as _s
        s = _s.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        srv = fastwire.FastServer(port, {"SendVariable": lambda req: req},
                                  addr="127.0.0.1")
        pool = fastwire.FastConnPool(0)
        conn = pool.checkout("127.0.0.1:%d" % port)
        if conn is not None:
            payload = _enc_tensor_parts("w", param)
            conn.call("SendVariable", payload)      # warm
            t0 = time.perf_counter()
            conn.call("SendVariable", payload)
            dt = time.perf_counter() - t0
            floor["echo_roundtrip_%dmb_s" % round(mb)] = round(dt, 3)
            floor["echo_mb_per_sec_both_ways"] = round(2 * mb / dt, 0)
            pool.discard(conn)
        srv.stop()

    # server aggregate (2-trainer mean into an aligned buffer) + SGD
    import jax
    g0, g1 = param, param
    w = jax.device_put(param).block_until_ready()
    sgd = jax.jit(lambda w, g: w - 0.01 * g)
    sgd(w, param).block_until_ready()               # warm/compile
    t0 = time.perf_counter()
    agg = _aligned_empty(param.shape, param.dtype)
    np.add(g0, g1, out=agg)
    agg *= 0.5
    sgd(w, agg).block_until_ready()
    floor["server_aggregate_plus_sgd_s"] = round(
        time.perf_counter() - t0, 3)
    return floor


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="pserver round-throughput benchmark "
                    "(2x2 localhost, real transpiled programs)")
    ap.add_argument("--quick", action="store_true",
                    help="small param + few rounds: a seconds-scale "
                    "smoke of the full data plane (CI tier-1)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the JSON line to PATH")
    ap.add_argument("--no-floor", action="store_true",
                    help="skip the component-floor measurements")
    ap.add_argument("--no-ctr", action="store_true",
                    help="skip the CTR-shaped scenario")
    ap.add_argument("--no-sweep", action="store_true",
                    help="skip the dense compression-codec sweep")
    ap.add_argument("--sentinel", action="store_true",
                    help="gate this run against PERF_TRAJECTORY.json "
                         "via tools/perf_sentinel.py (rc 3 on a >15%% "
                         "regression vs the recorded floor; quick "
                         "runs only compare against quick floors).  "
                         "ROADMAP: always pass this")
    args = ap.parse_args(argv)

    if args.quick:
        # must be exported BEFORE bench() spawns: children re-import
        # this module and re-derive the model dims from the env
        os.environ.setdefault("PSB_DENSE_IN", "1024")
        os.environ.setdefault("PSB_DENSE_OUT", "1600")
        os.environ.setdefault("PSB_VOCAB", "50000")
        os.environ.setdefault("PSB_SPARSE_BATCH", "256")
        os.environ.setdefault("PSB_DENSE_STEPS", "3")
        os.environ.setdefault("PSB_SPARSE_STEPS", "3")
        os.environ.setdefault("PSB_CTR_STEPS", "3")
        os.environ.setdefault("PSB_CTR_VOCAB", "200000")
        os.environ.setdefault("PSB_CTR_BATCH", "512")
        global DENSE_IN, DENSE_OUT, VOCAB, SPARSE_BATCH
        global CTR_VOCAB, CTR_BATCH
        DENSE_IN = int(os.environ["PSB_DENSE_IN"])
        DENSE_OUT = int(os.environ["PSB_DENSE_OUT"])
        VOCAB = int(os.environ["PSB_VOCAB"])
        SPARSE_BATCH = int(os.environ["PSB_SPARSE_BATCH"])
        CTR_VOCAB = int(os.environ["PSB_CTR_VOCAB"])
        CTR_BATCH = int(os.environ["PSB_CTR_BATCH"])
    dense_steps = int(os.environ.get("PSB_DENSE_STEPS", "20"))
    sparse_steps = int(os.environ.get("PSB_SPARSE_STEPS", "50"))
    ctr_steps = int(os.environ.get("PSB_CTR_STEPS", "12"))

    # the headline dense/sparse numbers stay codec-free (comparable
    # round over round); the sweep and the CTR scenario carry the
    # ISSUE 10 knobs explicitly
    base_env = {"FLAGS_dist_compress":
                os.environ.get("FLAGS_dist_compress", "")}
    dense_rps, _ = bench("dense", dense_steps, base_port=19310,
                         env=base_env)
    sparse_rps, _ = bench("sparse", sparse_steps, base_port=19330,
                          env=base_env)

    dense_mb = DENSE_IN * DENSE_OUT * 4 / 1e6
    # per sync round the server side moves, per trainer: grad up +
    # fresh param down; aggregate wire traffic = 2 trainers x 2 dirs
    wire_mb_s = dense_rps * dense_mb * 2 * 2
    # distinct rows actually touched per step (2 trainers' batches)
    distinct = distinct_rows_per_step("sparse", min(8, sparse_steps))
    rows_s = sparse_rps * distinct
    round_ms = 1000.0 / dense_rps
    out = {
        "metric": "pserver_bench",
        "quick": bool(args.quick),
        "dense_param_mb": round(dense_mb, 1),
        "dense_rounds_per_sec": round(dense_rps, 2),
        "dense_wire_mb_per_sec": round(wire_mb_s, 1),
        "dense_round_ms": round(round_ms, 1),
        "sparse_rows_per_sec": round(rows_s, 0),
        "sparse_steps_per_sec": round(sparse_rps, 2),
        "pservers": 2,
        "trainers": 2,
        # the "can it feed one chip" statement: a 100 ms accelerator
        # step overlapped 1:1 with a sync round of this 100 MB model
        "fraction_of_chip_step": round(round_ms / 100.0, 2),
    }
    if not args.no_sweep:
        try:
            out["dense_compress"] = compress_sweep(
                max(3, dense_steps // 3), base_port=19400)
        except Exception as e:
            out["dense_compress_error"] = str(e)[:200]
    if not args.no_ctr:
        try:
            out["ctr"] = ctr_bench(ctr_steps, base_port=19600)
        except Exception as e:
            out["ctr_error"] = str(e)[:200]
    if not args.no_floor:
        try:
            out["component_floor"] = component_floor()
        except Exception as e:   # floors are evidence, not the metric
            out["component_floor_error"] = str(e)[:200]
    line = json.dumps(out)
    print(line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")
    if args.sentinel:
        # perf sentinel (ISSUE 13): rc 3 when a measured metric
        # regresses >15% against its recorded PERF_TRAJECTORY floor
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from perf_sentinel import sentinel_gate

        return sentinel_gate(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
