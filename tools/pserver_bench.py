"""Pserver throughput microbenchmark (round-3 VERDICT weak #3).

The reference's C++ ParameterServer2 (paddle/pserver/ParameterServer2.h)
was a performance component: sharded updates, zero-copy sockets.  Its
replacement here is the Python gRPC pserver (distributed/rpc.py) behind
the distribute transpiler.  This tool measures what that pserver
actually sustains on localhost, end to end through the REAL training
path (transpiled programs, 2 trainers, sync mode):

  dense  — one ~100 MB fc parameter: full grad up + param down every
           round; reports rounds/sec and the aggregate wire MB/s the
           server moved.
  sparse — a 1M-row x 64 embedding with is_sparse=True: per-step
           SelectedRows updates; reports touched rows/sec.

Run:  python tools/pserver_bench.py  (writes one JSON line to stdout)

The JSON includes `fraction_of_chip_step`: with the measured round
time, the share of a 100 ms accelerator step (the ResNet-50 headline's
step time) a synchronous round would consume if overlapped 1:1 — the
"can this pserver feed one chip" statement the VERDICT asked for.
"""
import json
import multiprocessing as mp
import os
import sys
import time

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=2")
# FORCE cpu (not setdefault): the pserver bench is a host-path benchmark
# by definition; a rig-exported JAX_PLATFORMS must not pull in a (maybe
# dead) accelerator tunnel
os.environ["JAX_PLATFORMS"] = "cpu"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np

# dense: 4096 x 6400 f32 = 104.9 MB parameter.  Env-overridable (not
# argv): spawn children re-import this module fresh, so the quick-mode
# dims must travel through the environment to reach them.
DENSE_IN = int(os.environ.get("PSB_DENSE_IN", "4096"))
DENSE_OUT = int(os.environ.get("PSB_DENSE_OUT", "6400"))
DENSE_BATCH = 8
# sparse: 1M x 64 embedding, 1024 samples x 4 ids per step
VOCAB = int(os.environ.get("PSB_VOCAB", "1000000"))
EMB_DIM = 64
SPARSE_BATCH = int(os.environ.get("PSB_SPARSE_BATCH", "1024"))
IDS_PER_SAMPLE = 4


def build_model(kind):
    import paddle_tpu.fluid as fluid

    zinit = fluid.initializer.ConstantInitializer(0.0)
    if kind == "sparse":
        ids = fluid.layers.data(name="ids", shape=[IDS_PER_SAMPLE],
                                dtype="int64")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        # distributed lookup table (the DeepFM-style workload SURVEY
        # §2.5 keeps the pserver path FOR): trainers prefetch only the
        # batch's rows and push SelectedRows updates — no full-table
        # sync per round
        emb = fluid.layers.embedding(
            ids, size=[VOCAB, EMB_DIM], is_sparse=True,
            is_distributed=True,
            param_attr=fluid.ParamAttr(
                name="emb_w",
                initializer=fluid.initializer.ConstantInitializer(0.02)))
        pooled = fluid.layers.reduce_mean(emb, dim=1)
        pred = fluid.layers.fc(
            input=pooled, size=1,
            param_attr=fluid.ParamAttr(name="fc_w",
                                       initializer=zinit),
            bias_attr=fluid.ParamAttr(name="fc_b", initializer=zinit))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
    else:
        x = fluid.layers.data(name="x", shape=[DENSE_IN],
                              dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(
            input=x, size=DENSE_OUT,
            param_attr=fluid.ParamAttr(name="big_w", initializer=zinit),
            bias_attr=False)
        pred = fluid.layers.fc(
            input=h, size=1,
            param_attr=fluid.ParamAttr(name="head_w",
                                       initializer=zinit),
            bias_attr=False)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return loss


def make_batch(step, kind):
    rng = np.random.RandomState(step)
    if kind == "sparse":
        return {
            "ids": rng.randint(0, VOCAB,
                               (SPARSE_BATCH, IDS_PER_SAMPLE)
                               ).astype(np.int64),
            "y": rng.rand(SPARSE_BATCH, 1).astype(np.float32),
        }
    return {
        "x": rng.rand(DENSE_BATCH, DENSE_IN).astype(np.float32),
        "y": rng.rand(DENSE_BATCH, 1).astype(np.float32),
    }


def _transpile(trainer_id, pservers, trainers, kind):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = build_model(kind)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=trainer_id, program=main,
                startup_program=startup, pservers=pservers,
                trainers=trainers, sync_mode=True)
    return t, main, startup, scope, loss


def run_pserver(endpoint, pservers, trainers, kind):
    import paddle_tpu.fluid as fluid

    t, main, startup, scope, loss = _transpile(0, pservers, trainers,
                                               kind)
    ps_prog = t.get_pserver_program(endpoint)
    ps_startup = t.get_startup_program(endpoint, ps_prog)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(ps_startup)
        exe.run(ps_prog)


def run_trainer(trainer_id, pservers, trainers, steps, queue, kind):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed.rpc import RPCClient

    t, main, startup, scope, loss = _transpile(trainer_id, pservers,
                                               trainers, kind)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        prog = t.get_trainer_program()
        exe.run(prog, feed=make_batch(0, kind),
                fetch_list=[loss])             # warm / compile
        t0 = time.time()
        for s in range(1, steps + 1):
            exe.run(prog, feed=make_batch(s, kind), fetch_list=[loss])
        dt = time.time() - t0
    RPCClient.instance().send_complete(t.pserver_endpoints)
    queue.put((trainer_id, dt, steps))


def bench(kind, steps, n_pservers=2, n_trainers=2, base_port=19310):
    ctx = mp.get_context("spawn")
    eps = ["127.0.0.1:%d" % (base_port + i) for i in range(n_pservers)]
    pservers = ",".join(eps)
    ps_procs = [ctx.Process(target=run_pserver,
                            args=(ep, pservers, n_trainers, kind))
                for ep in eps]
    tr_procs = []
    try:
        for p in ps_procs:
            p.start()
        time.sleep(2.0)
        q = ctx.Queue()
        tr_procs = [ctx.Process(target=run_trainer,
                                args=(i, pservers, n_trainers, steps, q,
                                      kind))
                    for i in range(n_trainers)]
        for p in tr_procs:
            p.start()
        results = [q.get(timeout=900) for _ in tr_procs]
        for p in tr_procs + ps_procs:
            p.join(timeout=120)
    finally:
        # a crashed child must not leave non-daemon orphans holding the
        # ports (and blocking interpreter exit)
        for p in tr_procs + ps_procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)
    dt = max(r[1] for r in results)  # rounds complete at the slowest
    return steps / dt


def component_floor():
    """Measure the round's component floors on THIS machine: the
    fastwire echo (wire both ways), the batched frame encode+decode,
    and the server's aggregate+SGD — so the headline number comes with
    its decomposition instead of a guess."""
    from paddle_tpu.distributed import fastwire
    from paddle_tpu.distributed.rpc import (_dec_tensor,
                                            _enc_tensor_parts,
                                            _iter_batch, _enc_batch_parts,
                                            _aligned_empty)

    floor = {}
    param = np.ones((DENSE_IN, DENSE_OUT), np.float32)
    mb = param.nbytes / 1e6

    # batched frame encode (parts, no join) + zero-copy decode over a
    # received-style buffer.  The join below happens OUTSIDE the timer:
    # the wire never pays it (vectored send / recv-into-one-buffer) —
    # this floor is the actual per-round framing overhead
    parts = _enc_batch_parts([_enc_tensor_parts("w", param)])
    joined = b"".join(bytes(p) if isinstance(p, bytes) else p.tobytes()
                      for p in parts)
    view = memoryview(joined)
    t0 = time.perf_counter()
    _enc_batch_parts([_enc_tensor_parts("w", param)])
    for frame in _iter_batch(view):
        _dec_tensor(frame)
    floor["enc_dec_%dmb_s" % round(mb)] = round(
        time.perf_counter() - t0, 4)

    if fastwire.native_available():
        import socket as _s
        s = _s.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        srv = fastwire.FastServer(port, {"SendVariable": lambda req: req},
                                  addr="127.0.0.1")
        pool = fastwire.FastConnPool(0)
        conn = pool.checkout("127.0.0.1:%d" % port)
        if conn is not None:
            payload = _enc_tensor_parts("w", param)
            conn.call("SendVariable", payload)      # warm
            t0 = time.perf_counter()
            conn.call("SendVariable", payload)
            dt = time.perf_counter() - t0
            floor["echo_roundtrip_%dmb_s" % round(mb)] = round(dt, 3)
            floor["echo_mb_per_sec_both_ways"] = round(2 * mb / dt, 0)
            pool.discard(conn)
        srv.stop()

    # server aggregate (2-trainer mean into an aligned buffer) + SGD
    import jax
    g0, g1 = param, param
    w = jax.device_put(param).block_until_ready()
    sgd = jax.jit(lambda w, g: w - 0.01 * g)
    sgd(w, param).block_until_ready()               # warm/compile
    t0 = time.perf_counter()
    agg = _aligned_empty(param.shape, param.dtype)
    np.add(g0, g1, out=agg)
    agg *= 0.5
    sgd(w, agg).block_until_ready()
    floor["server_aggregate_plus_sgd_s"] = round(
        time.perf_counter() - t0, 3)
    return floor


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="pserver round-throughput benchmark "
                    "(2x2 localhost, real transpiled programs)")
    ap.add_argument("--quick", action="store_true",
                    help="small param + few rounds: a seconds-scale "
                    "smoke of the full data plane (CI tier-1)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the JSON line to PATH")
    ap.add_argument("--no-floor", action="store_true",
                    help="skip the component-floor measurements")
    args = ap.parse_args(argv)

    if args.quick:
        # must be exported BEFORE bench() spawns: children re-import
        # this module and re-derive the model dims from the env
        os.environ.setdefault("PSB_DENSE_IN", "1024")
        os.environ.setdefault("PSB_DENSE_OUT", "1600")
        os.environ.setdefault("PSB_VOCAB", "50000")
        os.environ.setdefault("PSB_SPARSE_BATCH", "256")
        os.environ.setdefault("PSB_DENSE_STEPS", "3")
        os.environ.setdefault("PSB_SPARSE_STEPS", "3")
        global DENSE_IN, DENSE_OUT, VOCAB, SPARSE_BATCH
        DENSE_IN = int(os.environ["PSB_DENSE_IN"])
        DENSE_OUT = int(os.environ["PSB_DENSE_OUT"])
        VOCAB = int(os.environ["PSB_VOCAB"])
        SPARSE_BATCH = int(os.environ["PSB_SPARSE_BATCH"])
    dense_steps = int(os.environ.get("PSB_DENSE_STEPS", "20"))
    sparse_steps = int(os.environ.get("PSB_SPARSE_STEPS", "50"))

    dense_rps = bench("dense", dense_steps, base_port=19310)
    sparse_rps = bench("sparse", sparse_steps, base_port=19330)

    dense_mb = DENSE_IN * DENSE_OUT * 4 / 1e6
    # per sync round the server side moves, per trainer: grad up +
    # fresh param down; aggregate wire traffic = 2 trainers x 2 dirs
    wire_mb_s = dense_rps * dense_mb * 2 * 2
    # distinct rows actually touched per step (2 trainers' batches)
    rng = np.random.RandomState(1)
    probe = rng.randint(0, VOCAB, (2 * SPARSE_BATCH * IDS_PER_SAMPLE,))
    distinct = len(np.unique(probe))
    rows_s = sparse_rps * distinct
    round_ms = 1000.0 / dense_rps
    out = {
        "metric": "pserver_bench",
        "quick": bool(args.quick),
        "dense_param_mb": round(dense_mb, 1),
        "dense_rounds_per_sec": round(dense_rps, 2),
        "dense_wire_mb_per_sec": round(wire_mb_s, 1),
        "dense_round_ms": round(round_ms, 1),
        "sparse_rows_per_sec": round(rows_s, 0),
        "sparse_steps_per_sec": round(sparse_rps, 2),
        "pservers": 2,
        "trainers": 2,
        # the "can it feed one chip" statement: a 100 ms accelerator
        # step overlapped 1:1 with a sync round of this 100 MB model
        "fraction_of_chip_step": round(round_ms / 100.0, 2),
    }
    if not args.no_floor:
        try:
            out["component_floor"] = component_floor()
        except Exception as e:   # floors are evidence, not the metric
            out["component_floor_error"] = str(e)[:200]
    line = json.dumps(out)
    print(line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
