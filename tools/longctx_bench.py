#!/usr/bin/env python
"""Long-context frontier bench (ISSUE 15 tentpole): ring attention vs
the non-ring flash baseline, 8k-128k tokens on the 8-device mesh.

Measures, per sequence length, a full fwd+bwd attention step (the
training hot path) in an ISOLATED child process per point:

- **ring**: parallel/ring.py over ``{"sp": p}`` — flash-chunk inner
  compute, double-buffered K/V rotation, causal block skipping, the
  saved-lse reverse-ring backward.  tokens/s, step wall, peak RSS.
- **baseline**: the dense single-program flash path
  (kernels/flash_attention.py; the XLA fallback off-TPU) at the same
  total sequence.  Its score block is O(S^2): the bench PREDICTS the
  footprint first and records ``oom_predicted`` instead of taking the
  host down; a child that dies anyway is recorded as ``collapsed``.
  Either record satisfies the acceptance gate — that collapse is the
  point.

The smallest ring point also collects the structural evidence:

- **parity**: ring fwd+bwd vs the single-device flash fallback
  (<= 1e-5 fp32, the acceptance pin);
- **skip**: ``causal_step_counts`` — executed chunks per ring position
  ([1..p]; sum p(p+1)/2 vs p^2 dense, ~2x fewer FLOPs at p=8);
- **hlo**: the optimized-HLO collective inventory
  (MESH_PROFILE_r06.md method, via ``jit(...).lower().compile()
  .as_text()``): the double-buffered forward schedules exactly
  2*(p-1) collective-permutes (the naive scan rotates 2*p) and the
  causal skip contributes p-1 ``conditional`` branches.

Writes ``LONGCTX_BENCH.json`` (--out); ``--quick`` is the seconds-long
tier-1 smoke (wired in tests/test_ring_longctx.py); ``--sentinel``
gates the run against PERF_TRAJECTORY.json floors (ROADMAP: always
pass it).

Usage:
    python tools/longctx_bench.py --out LONGCTX_BENCH.json --sentinel
    python tools/longctx_bench.py --quick
"""
import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

FULL_SEQS = (8192, 32768, 65536, 131072)
QUICK_SEQS = (2048, 4096)
PARITY_TOL = 1e-5
# fwd+bwd slabs the dense XLA fallback holds live per attention step
# (s, p, dp, ds + the grad-of-softmax temp): the OOM predictor's
# multiplier over the raw [B, H, S, S] f32 score block
BASELINE_SLABS = 5

# opcode-position matches only (the opcode is directly followed by its
# operand list) — a bare word match would also count every %name
# operand reference and inflate the inventory
_COLL_RE = re.compile(
    r"\b(collective-permute-start|collective-permute|conditional)\(")


def _mem_budget_bytes():
    budget = os.environ.get("LONGCTX_MEM_BUDGET_MB")
    if budget:
        return int(budget) * (1 << 20)
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024 * 7 // 10
    except OSError:
        pass
    return 8 << 30


def _peak_rss_mb():
    import resource

    ru = resource.getrusage(resource.RUSAGE_SELF)
    return round(ru.ru_maxrss / 1024.0, 1)   # linux: KB


# ------------------------------------------------------------ children

def _child_inputs(args):
    import numpy as np
    import jax.numpy as jnp

    rng = np.random.RandomState(7)
    shape = (args.batch, args.heads, args.seq, args.head_dim)
    # randn scaled down so softmax at long S stays in a realistic range
    return tuple(jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.5)
                 for _ in range(3))


def _timed(step, ops, steps):
    import numpy as np

    float(np.asarray(step(*ops)))            # warmup + compile
    t0 = time.time()
    for _ in range(steps):
        r = step(*ops)
    float(np.asarray(r))                     # d2h drain = the only sync
    return (time.time() - t0) / steps


def _run_ring_child(args):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.flags import apply_xla_flags
    apply_xla_flags()
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.ring import ring_attention

    p = args.devices
    mesh = make_mesh({"sp": p}, devices=jax.devices("cpu")[:p])
    q, k, v = _child_inputs(args)

    def loss(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=True)
        return (o.astype(jnp.float32) ** 2).sum()

    grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def step(q, k, v):
        dq, dk, dv = grad(q, k, v)
        return dq[0, 0, 0, 0] + dk[0, 0, 0, 0] + dv[0, 0, 0, 0]

    sec = _timed(step, (q, k, v), args.steps)
    tokens = args.batch * args.seq
    out = {
        "mode": "ring", "seq": args.seq,
        "step_s": round(sec, 4),
        "tokens_s": round(tokens / sec, 1),
        "tokens_s_per_device": round(tokens / sec / p, 1),
        "peak_rss_mb": _peak_rss_mb(),
    }
    if args.extras:
        out.update(_ring_extras(args, mesh, q, k, v))
    print(json.dumps(out))
    return 0


def _ring_extras(args, mesh, q, k, v):
    """Parity + causal-skip + HLO structure evidence, collected once at
    the smallest ring point (compiles are cheap there)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels.flash_attention import flash_attention
    from paddle_tpu.parallel.ring import (causal_step_counts,
                                          ring_attention,
                                          ring_attention_fwd_lse)

    p = args.devices
    # --- fwd+bwd parity vs the single-device flash fallback
    out_ring = ring_attention(q, k, v, mesh, causal=True)
    out_ref = flash_attention(q, k, v, causal=True)
    fwd_diff = float(jnp.abs(out_ring - out_ref).max())

    def loss_ring(q):
        return (ring_attention(q, k, v, mesh, causal=True)
                .astype(jnp.float32) ** 2).sum()

    def loss_ref(q):
        return (flash_attention(q, k, v, causal=True)
                .astype(jnp.float32) ** 2).sum()

    g_ring = jax.grad(loss_ring)(q)
    g_ref = jax.grad(loss_ref)(q)
    scale_ref = float(jnp.abs(g_ref).max()) or 1.0
    bwd_diff = float(jnp.abs(g_ring - g_ref).max()) / scale_ref
    parity = {"fwd_maxdiff": fwd_diff, "bwd_rel_maxdiff": bwd_diff,
              "tol": PARITY_TOL,
              "ok": fwd_diff <= PARITY_TOL and bwd_diff <= PARITY_TOL}

    # --- causal block skipping: executed chunks per ring position
    counts = [int(c) for c in np.asarray(causal_step_counts(mesh))]
    executed = sum(counts)
    skip = {"counts": counts, "executed_chunks": executed,
            "dense_chunks": p * p,
            "flop_ratio": round(executed / float(p * p), 4),
            "ok": counts == list(range(1, p + 1))}

    # --- optimized-HLO inventory (the MESH_PROFILE_r06.md method):
    # forward module alone so the expected counts are exact
    def fwd(q, k, v):
        return ring_attention_fwd_lse(q, k, v, mesh, causal=True)[0]

    txt = jax.jit(fwd).lower(q, k, v).compile().as_text()
    hits = {}
    for mm in _COLL_RE.finditer(txt):
        hits[mm.group(1)] = hits.get(mm.group(1), 0) + 1
    permutes = hits.get("collective-permute", 0) \
        + hits.get("collective-permute-start", 0)
    conds = hits.get("conditional", 0)
    hlo = {
        "collective_permute": permutes,
        "collective_permute_start": hits.get(
            "collective-permute-start", 0),
        "conditional": conds,
        # double-buffered forward: K and V each rotate p-1 times (the
        # last rotation is elided); the naive scan rotated both p times
        "expected_permutes": 2 * (p - 1),
        "naive_scan_permutes": 2 * p,
        # p-1 cond-guarded off-diagonal steps under causal
        "expected_conditionals": p - 1,
        "double_buffer_structure": permutes == 2 * (p - 1),
        "causal_skip_structure": conds >= p - 1,
    }
    return {"parity": parity, "skip": skip, "hlo": hlo}


def _run_baseline_child(args):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.flags import apply_xla_flags
    apply_xla_flags()
    from paddle_tpu.kernels.flash_attention import flash_attention

    q, k, v = _child_inputs(args)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True)
        return (o.astype(jnp.float32) ** 2).sum()

    grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def step(q, k, v):
        dq, dk, dv = grad(q, k, v)
        return dq[0, 0, 0, 0] + dk[0, 0, 0, 0] + dv[0, 0, 0, 0]

    sec = _timed(step, (q, k, v), args.steps)
    tokens = args.batch * args.seq
    print(json.dumps({
        "mode": "baseline", "seq": args.seq,
        "step_s": round(sec, 4),
        "tokens_s": round(tokens / sec, 1),
        "peak_rss_mb": _peak_rss_mb(),
    }))
    return 0


# ------------------------------------------------------------ parent

def _spawn(mode, seq, args, extras=False):
    env = dict(os.environ)
    dev = args.devices if mode == "ring" else 1
    flags = [t for t in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in t]
    flags.append("--xla_force_host_platform_device_count=%d" % dev)
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    steps = args.steps if seq < 65536 else max(1, args.steps // 2)
    cmd = [sys.executable, os.path.abspath(__file__), "--child", mode,
           "--seq", str(seq), "--devices", str(args.devices),
           "--batch", str(args.batch), "--heads", str(args.heads),
           "--head-dim", str(args.head_dim), "--steps", str(steps)]
    if extras:
        cmd.append("--extras")
    timeout = float(os.environ.get(
        "LONGCTX_CHILD_TIMEOUT", "240" if args.quick else "3600"))
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"collapsed": True, "reason": "timeout",
                "timeout_s": timeout}
    if proc.returncode != 0:
        return {"collapsed": True, "rc": proc.returncode,
                "wall_s": round(time.time() - t0, 1),
                "stderr_tail": proc.stderr[-400:]}
    line = proc.stdout.strip().splitlines()[-1]
    try:
        return json.loads(line)
    except ValueError:
        return {"collapsed": True, "rc": 0,
                "reason": "unparseable child output",
                "stdout_tail": proc.stdout[-400:]}


def _baseline_point(seq, args, budget):
    est = (args.batch * args.heads * seq * seq * 4) * BASELINE_SLABS
    if est > budget:
        # the expected long-context story: the dense score block alone
        # does not fit — record the OOM instead of taking the rig down
        return {"oom_predicted": True, "estimated_bytes": est,
                "budget_bytes": budget}
    return _spawn("baseline", seq, args)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="ring vs dense-flash long-context bench "
                    "(tokens/s + peak memory vs sequence length)")
    ap.add_argument("--quick", action="store_true",
                    help="seconds-long tier-1 smoke (2k/4k, small "
                         "heads)")
    ap.add_argument("--out", default="",
                    help="also write the JSON artifact here")
    ap.add_argument("--seqs", default="",
                    help="comma-separated sequence lengths (default "
                         "8192,32768,65536,131072; quick 2048,4096)")
    ap.add_argument("--devices", type=int,
                    default=int(os.environ.get("LONGCTX_DEVICES", "8")),
                    help="ring width p (simulated host devices)")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--heads", type=int, default=0,
                    help="attention heads (default 2; the bench "
                         "stresses the sequence axis, not d_model)")
    ap.add_argument("--head-dim", type=int, default=0,
                    help="head dim (default 64 full / 32 quick)")
    ap.add_argument("--steps", type=int, default=0,
                    help="timed steps per point (default 2; halved "
                         "past 64k)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the dense baseline points")
    ap.add_argument("--sentinel", action="store_true",
                    help="gate this run against PERF_TRAJECTORY.json "
                         "via tools/perf_sentinel.py (rc 3 on a >15%% "
                         "regression vs the recorded floor).  ROADMAP: "
                         "always pass this")
    ap.add_argument("--json", action="store_true",
                    help="pretty-print the artifact")
    # child plumbing
    ap.add_argument("--child", default="", choices=("", "ring",
                                                    "baseline"))
    ap.add_argument("--seq", type=int, default=0)
    ap.add_argument("--extras", action="store_true")
    args = ap.parse_args(argv)

    args.heads = args.heads or 2
    args.head_dim = args.head_dim or (32 if args.quick else 64)
    args.steps = args.steps or 2

    if args.child:
        return (_run_ring_child(args) if args.child == "ring"
                else _run_baseline_child(args))

    seqs = tuple(int(s) for s in args.seqs.split(",") if s) or \
        (QUICK_SEQS if args.quick else FULL_SEQS)
    budget = _mem_budget_bytes()
    points = []
    extras = {}
    for i, seq in enumerate(sorted(seqs)):
        ring = _spawn("ring", seq, args, extras=(i == 0))
        for key in ("parity", "skip", "hlo"):
            if key in ring:
                extras[key] = ring.pop(key)
        point = {"seq": seq, "ring": ring}
        if not args.no_baseline:
            point["baseline"] = _baseline_point(seq, args, budget)
            base = point["baseline"]
            if ring.get("tokens_s") and base.get("tokens_s"):
                point["ring_vs_baseline"] = round(
                    ring["tokens_s"] / base["tokens_s"], 2)
        points.append(point)
        print("# %s" % json.dumps(point), file=sys.stderr)

    ring_ok = all(not pt["ring"].get("collapsed") for pt in points)
    # acceptance: at 64k the ring is >= 2x the baseline, or the
    # baseline's OOM/collapse is on record
    gate_seq = 65536
    gate = None
    for pt in points:
        if pt["seq"] == gate_seq and "baseline" in pt:
            base = pt["baseline"]
            if base.get("oom_predicted") or base.get("collapsed"):
                gate = {"seq": gate_seq, "baseline_oom": True,
                        "ok": True}
            else:
                r = pt.get("ring_vs_baseline") or 0.0
                gate = {"seq": gate_seq, "baseline_oom": False,
                        "ring_vs_baseline": r, "ok": r >= 2.0}
    out = {
        "metric": "longctx_bench",
        "quick": bool(args.quick),
        "platform": os.environ.get("JAX_PLATFORMS", "cpu"),
        "p": args.devices,
        "dims": {"batch": args.batch, "heads": args.heads,
                 "head_dim": args.head_dim, "dtype": "float32",
                 "fwd_bwd": True},
        "mem_budget_bytes": budget,
        "points": points,
        "ok": bool(
            ring_ok
            and extras.get("parity", {}).get("ok")
            and extras.get("skip", {}).get("ok")
            and extras.get("hlo", {}).get("double_buffer_structure")
            and extras.get("hlo", {}).get("causal_skip_structure")
            and (gate is None or gate["ok"])),
    }
    out.update(extras)
    if gate is not None:
        out["gate_64k"] = gate
    line = json.dumps(out)
    print(json.dumps(out, indent=2) if args.json else line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    rc = 0 if out["ok"] else 1
    if rc or not args.sentinel:
        return rc
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from perf_sentinel import sentinel_gate

    return sentinel_gate(out)


if __name__ == "__main__":
    sys.exit(main())
