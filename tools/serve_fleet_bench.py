#!/usr/bin/env python
"""Disaggregated serving fleet bench -> SERVE_FLEET_BENCH.json
(ISSUE 16 proof harness).

What it measures, on the same tiny-LM family serve_bench.py uses:

1. **Solo floor** — one solo worker process (prefill + decode in one
   loop, no migration) behind the router, serial closed-loop
   requests: the single-request tok/s floor (serve_bench's
   `serve_gen_floor_tokens_s` discipline).
2. **Fleet scaling** — 2 prefill + 4 decode worker processes under
   saturating open-loop Poisson arrivals, and the SAME trace against
   the solo monolith.  The scaling gate is rig-honest: with >= 4
   cores the >= 4 decode replicas must clear 2.5x the solo floor; on
   this single-core CI rig process parallelism cannot multiply
   throughput, so the gate is aggregate batch WIDTH (4 replicas x 16
   rows amortizing per-step dispatch cost) beating the serial solo
   floor >= 1.1x net of all migration/wire overhead, with the
   fleet-vs-monolith ratio reported unvarnished alongside.
3. **Prefill burst** — steady decode traffic with a burst of
   max-length prompts dropped mid-run, against (a) the monolithic
   solo worker and (b) the fleet.  The monolith runs every prefill
   inline in its single decode loop, so the burst STALLS running
   requests' inter-token latency (the structural choke, measurable
   even when both systems share one core); fleet decode loops never
   execute a prefill, so their running ITL must hold at least 2x
   closer to baseline than the monolith's through the same burst.
4. **Kill drill** — the same precomputed Poisson schedule replayed
   twice: once healthy (baseline tokens), once with a decode worker
   SIGKILLed mid-run (`--kill both` also SIGKILLs a prefill worker).
   Gates: ZERO lost requests, greedy tokens bit-identical to the
   unkilled run, TTFT p99 recovers within 5 s of the kill, one
   flight artifact per eviction naming the dead worker, and the
   Watchtower `serve_fleet_availability` burn-rate alert fires.
5. **Torn migration** — fault-injected mid-payload tear on MigrateKV
   (in-process fleet, same codec): the destination must roll back its
   half-received blocks, raise the named BufferLifetimeError, and the
   request must still complete via the local-prefill fallback.

`--quick` runs the whole drill in-process over LocalTransport
(1 prefill + 2 decode, simulated kill) — the tier-1 CI smoke.
`--sentinel` self-gates the run against PERF_TRAJECTORY.json floors.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from paddle_tpu.core.flags import FLAGS                    # noqa: E402
from paddle_tpu.observability import metrics as _metrics   # noqa: E402

# one model family across every worker process (FLEETW_* env)
DIMS = {"FLEETW_SEED": "3", "FLEETW_VOCAB": "64",
        "FLEETW_DMODEL": "128", "FLEETW_HEADS": "4",
        "FLEETW_LAYERS": "3", "FLEETW_DFF": "256",
        "FLEETW_BLOCK": "16", "FLEETW_MAX_BLOCKS": "4",
        "FLEETW_KV_BLOCKS": "128", "FLEETW_MAX_BATCH": "16"}
VOCAB = 64
MAX_SEQ = 64          # block 16 x max_blocks 4


def _pctl(vals, p):
    if not vals:
        return 0.0
    from paddle_tpu.observability.metrics import nearest_rank
    return nearest_rank(sorted(vals), p)


def _counter(name):
    snap = _metrics.snapshot()
    entry = snap.get(name) or {}
    return float(entry.get("value") or 0.0)


# -- load generation ----------------------------------------------------

def _prompts(rng, n, lo, hi):
    return [[rng.randrange(VOCAB) for _ in range(rng.randrange(lo, hi))]
            for _ in range(n)]


def _schedule(seed, n, rate, lo=4, hi=24, prefix="r"):
    """Deterministic open-loop Poisson schedule: [(t_rel, rid, prompt)].
    Same seed => same arrivals, ids, prompts — the kill drill replays
    one schedule twice and diffs tokens."""
    rng = random.Random(seed)
    out, t = [], 0.0
    for i, p in enumerate(_prompts(rng, n, lo, hi)):
        t += rng.expovariate(rate)
        out.append((t, "%s%04d" % (prefix, i), p))
    return out


def _replay(router, schedule, max_new, kill_at=None, kill_fn=None,
            result_timeout=180.0):
    """Open-loop replay: submit on schedule regardless of completions,
    optionally firing kill_fn at t=kill_at, then resolve every future.
    Returns (records, summary)."""
    done_t, lock = {}, threading.Lock()
    futs = {}
    t0 = time.perf_counter()
    killed_rel = None
    i = 0
    while i < len(schedule):
        t_arr, rid, prompt = schedule[i]
        now = time.perf_counter() - t0
        if kill_fn is not None and killed_rel is None and now >= kill_at:
            kill_fn()
            killed_rel = time.perf_counter() - t0
            continue
        if now < t_arr:
            nxt = t_arr
            if kill_fn is not None and killed_rel is None:
                nxt = min(nxt, kill_at)
            time.sleep(min(0.05, max(0.0, nxt - now)))
            continue
        f = router.generate(prompt, max_new, req_id=rid)

        def _mark(fut, rid=rid):
            with lock:
                done_t[rid] = time.perf_counter()
        f.add_done_callback(_mark)
        futs[rid] = (t_arr, f)
        i += 1
    if kill_fn is not None and killed_rel is None:
        now = time.perf_counter() - t0
        if kill_at > now:
            time.sleep(kill_at - now)
        kill_fn()
        killed_rel = time.perf_counter() - t0
    recs = []
    deadline = time.perf_counter() + result_timeout
    for rid, (t_arr, f) in futs.items():
        try:
            r = f.result(timeout=max(0.1, deadline - time.perf_counter()))
            recs.append({"rid": rid, "t_arr": round(t_arr, 4), "ok": True,
                         "tokens": r["tokens"],
                         "ttft_ms": round(r["router_ttft_ms"], 3),
                         "itl_max_ms": round(max(r.get("itl_ms")
                                                 or [0.0]), 3),
                         "worker": r["worker"],
                         "reprefilled": r["reprefilled"],
                         "hedged": r["hedged"]})
        except Exception as e:
            recs.append({"rid": rid, "t_arr": round(t_arr, 4), "ok": False,
                         "error": "%s: %s" % (type(e).__name__, e)})
    ok = [r for r in recs if r["ok"]]
    toks = sum(len(r["tokens"]) for r in ok)
    span = (max(done_t.values()) - t0) if done_t else 1e-9
    summary = {
        "requests": len(recs), "completed": len(ok),
        "lost": len(recs) - len(ok),
        "tokens": toks,
        "span_s": round(span, 3),
        "tokens_s": round(toks / span, 1),
        "ttft_p50_ms": round(_pctl([r["ttft_ms"] for r in ok], 50), 2),
        "ttft_p99_ms": round(_pctl([r["ttft_ms"] for r in ok], 99), 2),
        "reprefilled": sum(r["reprefilled"] for r in ok),
        "hedged": sum(1 for r in ok if r["hedged"]),
    }
    if killed_rel is not None:
        summary["killed_at_s"] = round(killed_rel, 3)
    return recs, summary


def _serial_floor(router, seconds, max_new, seed=11):
    """Closed-loop single-request floor: one request at a time through
    one worker — the denominator of the scaling claim."""
    rng = random.Random(seed)
    toks = 0
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < seconds:
        p = [rng.randrange(VOCAB) for _ in range(rng.randrange(4, 24))]
        r = router.generate(p, max_new,
                            req_id="floor%05d" % n).result(timeout=120)
        toks += len(r["tokens"])
        n += 1
    dt = time.perf_counter() - t0
    return {"requests": n, "tokens": toks,
            "tokens_s": round(toks / dt, 1), "seconds": round(dt, 2)}


def _ttft_recovery(recs, killed_at, pre_p99, window=1.0, limit=60.0):
    """Seconds after the kill until a 1 s arrival window's worst TTFT
    drops back under max(2x pre-kill p99, 300 ms).  None = never."""
    thresh = max(2.0 * pre_p99, 300.0)
    post = [(r["t_arr"] - killed_at, r["ttft_ms"])
            for r in recs if r["ok"] and r["t_arr"] >= killed_at]
    if not post:
        return 0.0, thresh
    last = max(dt for dt, _ in post)
    w = 0.0
    while w <= min(last, limit):
        vals = [t for dt, t in post if w <= dt < w + window]
        if vals and max(vals) <= thresh:
            return round(w, 2), thresh
        w += window
    return None, thresh


def _eviction_artifacts(dump_dir, worker_names):
    """Flight artifacts written by router evictions, keyed by dead
    worker name."""
    found = {}
    for path in sorted(glob.glob(os.path.join(dump_dir, "flight_*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        reason = rec.get("reason", "")
        if not reason.startswith("fleet:eviction:"):
            continue
        name = (rec.get("blocked") or {}).get("worker")
        if name in worker_names:
            found.setdefault(name, []).append(os.path.basename(path))
    return found


# -- subprocess fleet (full mode) ---------------------------------------

class _Proc:
    def __init__(self, name, role, proc, log_path):
        self.name, self.role, self.proc = name, role, proc
        self.log_path = log_path
        self.addr = None
        self.exit = None


def _spawn_fleet(specs, log_dir, dump_dir):
    env = dict(os.environ)
    env.update(DIMS)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["FLAGS_telemetry_dump_dir"] = dump_dir
    procs = []
    for name, role in specs:
        log_path = os.path.join(log_dir, "%s.log" % name)
        logf = open(log_path, "w")
        p = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.fleet",
             "--role", role, "--name", name],
            stdout=subprocess.PIPE, stderr=logf, env=env, text=True)
        procs.append(_Proc(name, role, p, log_path))
    deadline = time.time() + 420.0
    for w in procs:
        line = ""
        while time.time() < deadline:
            line = w.proc.stdout.readline()
            if not line or line.startswith("FLEET_READY"):
                break
        if not line.startswith("FLEET_READY"):
            tail = ""
            try:
                with open(w.log_path) as f:
                    tail = "".join(f.readlines()[-12:])
            except OSError:
                pass
            raise RuntimeError("worker %s never came up: %r\n%s"
                               % (w.name, line, tail))
        fields = dict(kv.split("=") for kv in line.split()[1:])
        w.addr = "127.0.0.1:%s" % fields["port"]
    return procs


def _reap(procs, timeout=15.0):
    for w in procs:
        try:
            w.exit = w.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            w.proc.kill()
            w.exit = w.proc.wait(timeout=5.0)
    return {w.name: w.exit for w in procs}


def _drain_direct(transport, addr, timeout=60.0):
    from paddle_tpu.serving.fleet import M_CALL, decode_call, encode_call
    try:
        return decode_call(transport.call(
            addr, M_CALL,
            encode_call({"op": "drain", "timeout": timeout}),
            timeout=timeout + 5.0))
    except Exception as e:
        return {"ok": False, "error": str(e)}


def _fleet_migrations(transport, procs):
    """Sum migration counters over worker STATUS replies — the
    counters live in each worker subprocess's registry, so the bench
    process's own registry necessarily reads zero."""
    from paddle_tpu.serving.fleet import M_CALL, decode_call, encode_call
    total = dups = 0
    for w in procs:
        try:
            rep = decode_call(transport.call(
                w.addr, M_CALL, encode_call({"op": "status"}),
                timeout=5.0))
            c = rep.get("counters") or {}
            total += int(c.get("migrations", 0))
            dups += int(c.get("migration_dups", 0))
        except Exception:
            pass
    return total, dups


# -- torn-migration drill (in-process, both modes) ----------------------

def _torn_drill(dump_dir):
    """Deliberately tear a MigrateKV mid-payload: the receive must roll
    back, raise the NAMED BufferLifetimeError, and the request must
    still finish through the fallback path."""
    from paddle_tpu.distributed import resilience
    from paddle_tpu.serving.fleet import FleetWorker, LocalTransport
    from paddle_tpu.serving.generative import tiny_lm
    from paddle_tpu.serving.router import FleetRouter

    cfg, params = tiny_lm(3, vocab=VOCAB, d_model=32, n_heads=2,
                          n_layers=2, d_ff=64, block_size=16,
                          max_blocks=4, max_batch=4)
    tr = LocalTransport()
    workers = [FleetWorker(n, r, cfg, params, kv_blocks=24, warm=False,
                           transport=tr) for n, r in
               (("tp0", "prefill"), ("td0", "decode"))]
    for w in workers:
        tr.register(w)
    router = FleetRouter(tr, [(w.name, "local:%s" % w.name, w.role)
                              for w in workers],
                         lease_s=5.0, lease_interval_s=1.0,
                         deadline_s=60.0)
    rng = random.Random(7)
    prompt = [rng.randrange(VOCAB) for _ in range(10)]
    baseline = router.generate(prompt, 8, req_id="torn-ref") \
        .result(timeout=120)
    trips0 = _counter("sanitizer_trips_total")
    fails0 = _counter("fleet_migration_failures_total")
    pool_free0 = workers[1].engine.pool.free_blocks
    resilience.install_faults("fleet_migrate_tear:drop:1.0:1")
    try:
        r = router.generate(prompt, 8, req_id="torn-hit") \
            .result(timeout=120)
    finally:
        resilience.install_faults("")
    err = None
    for rec in router._recs.values():
        if rec.rid == "torn-hit" and rec.migrate_errors:
            err = rec.migrate_errors[0]
    # the fallback generation frees its blocks as the future resolves;
    # give the decode loop a beat before auditing the pool
    for _ in range(100):
        if workers[1].engine.pool.free_blocks == pool_free0:
            break
        time.sleep(0.02)
    pool_free1 = workers[1].engine.pool.free_blocks
    sanitizer_artifacts = [
        os.path.basename(p)
        for p in glob.glob(os.path.join(dump_dir, "flight_*.json"))
        if "sanitizer:buffer:kv_migration"
        in (json.load(open(p)).get("reason", "")
            if os.path.getsize(p) else "")]
    out = {
        "request_completed": r["tokens"] == baseline["tokens"],
        "error_kind": (err or {}).get("kind"),
        "error_names_request": "kv_migration:torn-hit"
                               in str((err or {}).get("error", "")),
        "rolled_back": "rolled back" in str((err or {}).get("error", "")),
        "dest_pool_restored": pool_free1 == pool_free0,
        "sanitizer_trips": _counter("sanitizer_trips_total") - trips0,
        "migration_failures":
            _counter("fleet_migration_failures_total") - fails0,
        "artifacts": sanitizer_artifacts,
    }
    out["ok"] = bool(out["request_completed"]
                     and out["error_kind"] == "BufferLifetimeError"
                     and out["error_names_request"]
                     and out["rolled_back"]
                     and out["dest_pool_restored"]
                     and out["sanitizer_trips"] >= 1)
    router.close()
    for w in workers:
        w.shutdown()
    return out


# -- SLO plane ----------------------------------------------------------

def _arm_slos(decode_names, tsdb_dir, dump_dir, ttft_p99_ms=5000.0):
    from paddle_tpu.observability import tsdb
    from paddle_tpu.serving.router import default_fleet_slos
    FLAGS.telemetry_dump_dir = dump_dir
    FLAGS.tsdb_dir = tsdb_dir
    FLAGS.tsdb_sample_ms = 100
    FLAGS.slo_spec = default_fleet_slos(decode_names,
                                        ttft_p99_ms=ttft_p99_ms)
    tsdb.ensure_sampler()


def _slo_verdict(await_s=0.0):
    """Evaluate the SLO plane; optionally poll up to ``await_s`` for
    the availability burn alert (samples accrue in real time)."""
    from paddle_tpu.observability import slo
    deadline = time.monotonic() + await_s
    while True:
        slo.evaluate_once()
        alerts = slo.active_alerts()
        fired = any(a["slo"] == "serve_fleet_availability"
                    for a in alerts)
        if fired or time.monotonic() >= deadline:
            return {
                "active_alerts": ["%s:%s" % (a["slo"], a["window"])
                                  for a in alerts],
                "availability_alert": fired,
            }
        time.sleep(0.25)


# -- modes --------------------------------------------------------------

def run_quick(args, dump_dir, tsdb_dir):
    """In-process tier-1 smoke: LocalTransport, 1 prefill + 2 decode,
    simulated kill, torn drill — every router/worker path, no ports."""
    from paddle_tpu.serving.fleet import FleetWorker, LocalTransport
    from paddle_tpu.serving.generative import tiny_lm
    from paddle_tpu.serving.router import FleetRouter

    cfg, params = tiny_lm(3, vocab=VOCAB, d_model=64, n_heads=4,
                          n_layers=2, d_ff=128, block_size=16,
                          max_blocks=4, max_batch=4)
    tr = LocalTransport()

    def mk(name, role):
        w = FleetWorker(name, role, cfg, params, kv_blocks=32,
                        warm=False, transport=tr)
        tr.register(w)
        return w

    solo = mk("s0", "decode")
    solo_router = FleetRouter(tr, [("s0", "local:s0", "decode")],
                              lease_s=5.0, lease_interval_s=1.0,
                              deadline_s=60.0)
    floor = _serial_floor(solo_router, seconds=1.5, max_new=args.max_new)
    solo_router.close()

    fleet = [mk("p0", "prefill"), mk("d0", "decode"), mk("d1", "decode")]
    members = [(w.name, "local:%s" % w.name, w.role) for w in fleet]
    _arm_slos(["d0", "d1"], tsdb_dir, dump_dir)
    router = FleetRouter(tr, members, lease_s=1.0, lease_interval_s=0.25,
                         hedge_s=2.0, deadline_s=60.0, max_attempts=5)
    mig0 = _counter("fleet_migrations_total")
    _, poisson = _replay(router, _schedule(21, 24, 12.0, prefix="q"),
                         args.max_new)

    sched = _schedule(22, 24, 12.0, prefix="k")
    base_recs, base = _replay(router, sched, args.max_new)
    base_map = {r["rid"]: r["tokens"] for r in base_recs if r["ok"]}
    ev0 = _counter("fleet_evictions_total")
    kill_recs, kill = _replay(router, sched, args.max_new,
                              kill_at=0.6, kill_fn=lambda: tr.kill("d1"))
    parity = all(r["ok"] and base_map.get(r["rid"]) == r["tokens"]
                 for r in kill_recs)
    slo_out = _slo_verdict(await_s=10.0)
    artifacts = _eviction_artifacts(dump_dir, {"d1"})
    torn = _torn_drill(dump_dir)
    drained = {}
    for w in fleet:
        if w.name != "d1":
            drained[w.name] = bool(router.drain(w.name).get("drained"))
    router.close()
    for w in fleet + [solo]:
        w.shutdown()
    out = {
        "mode": "quick", "replicas": 2,
        "floor": floor, "poisson": poisson,
        "kill": dict(kill, parity=parity,
                     evictions=_counter("fleet_evictions_total") - ev0,
                     artifacts=artifacts.get("d1", [])),
        "baseline": {"lost": base["lost"]},
        "migrations": _counter("fleet_migrations_total") - mig0,
        "slo": slo_out, "torn": torn, "drained": drained,
    }
    out["ok"] = bool(
        poisson["lost"] == 0 and base["lost"] == 0
        and kill["lost"] == 0 and parity
        and out["migrations"] > 0
        and out["kill"]["evictions"] >= 1
        and len(out["kill"]["artifacts"]) >= 1
        and slo_out["availability_alert"]
        and torn["ok"] and all(drained.values()))
    return out


def run_full(args, dump_dir, tsdb_dir):
    from paddle_tpu.serving.fleet import SocketTransport
    from paddle_tpu.serving.router import FleetRouter

    log_dir = tempfile.mkdtemp(prefix="fleet_logs_")
    replicas = int(args.replicas)
    prefills = int(args.prefill_workers)
    specs = [("s0", "decode")]
    specs += [("p%d" % i, "prefill") for i in range(prefills)]
    specs += [("d%d" % i, "decode") for i in range(replicas)]
    procs = _spawn_fleet(specs, log_dir, dump_dir)
    by_name = {w.name: w for w in procs}
    tr = SocketTransport()
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    out = {"mode": "full", "replicas": replicas,
           "prefill_workers": prefills,
           "rig": {"cores": cores},
           "worker_logs": log_dir}
    try:
        # -- 1. solo floor --------------------------------------------
        # no kill happens in phases 1-3: use a long lease so a worker
        # that is merely saturated (single-core rig) is never falsely
        # evicted — tight leases belong to the kill drill only
        solo_router = FleetRouter(
            tr, [("s0", by_name["s0"].addr, "decode")],
            lease_s=30.0, lease_interval_s=5.0, deadline_s=120.0)
        floor = _serial_floor(solo_router, args.floor_seconds,
                              args.max_new)
        out["floor"] = floor

        # -- 2. fleet scaling under saturating Poisson ----------------
        members = [(w.name, w.addr, w.role) for w in procs
                   if w.name != "s0"]
        router = FleetRouter(tr, members, lease_s=30.0,
                             lease_interval_s=5.0, deadline_s=120.0,
                             max_attempts=5)
        rate_rps = args.rate_x * floor["tokens_s"] / args.max_new
        n = max(8, int(rate_rps * args.seconds))
        # discarded warmup: the fleet's very first traffic wave runs
        # ~25% slow (thread/arena/conn ramp across 6 processes); the
        # measured replay starts from steady state
        _replay(router, _schedule(30, max(8, int(rate_rps * 4)),
                                  rate_rps, prefix="w"), args.max_new)
        mig0, _ = _fleet_migrations(tr, procs)
        _, scale = _replay(router, _schedule(31, n, rate_rps,
                                             prefix="s"),
                           args.max_new)
        scale["offered_rps"] = round(rate_rps, 2)
        scale["scaling_x"] = round(scale["tokens_s"]
                                   / max(1e-9, floor["tokens_s"]), 3)
        mig1, _ = _fleet_migrations(tr, procs)
        scale["migrations"] = mig1 - mig0
        # the same Poisson trace against the solo monolith: the honest
        # reference for what disaggregation costs (or buys) on this rig
        _, mono_scale = _replay(solo_router,
                                _schedule(31, n, rate_rps, prefix="sm"),
                                args.max_new)
        scale["monolith_tokens_s"] = mono_scale["tokens_s"]
        scale["monolith_lost"] = mono_scale["lost"]
        scale["fleet_vs_monolith_x"] = round(
            scale["tokens_s"] / max(1e-9, mono_scale["tokens_s"]), 3)
        # the scaling target is rig-honest: with >=4 cores the >=4
        # decode replicas must multiply throughput 2.5x over the serial
        # solo floor; on fewer cores the fleet and the floor share the
        # same silicon, so process parallelism can't multiply anything
        # — what must still win is aggregate batch WIDTH (4 replicas x
        # 16 rows vs one serial request), net of every migration/wire
        # overhead (measured 1.28x on the 1-core CI rig, gated at 1.1)
        scale["scaling_target"] = {1: 1.1, 2: 1.5, 3: 2.0}.get(
            cores, 2.5)
        out["scale"] = scale

        # -- 3. prefill burst: the isolation claim --------------------
        # On a rig where the fleet and the monolith share the same
        # core(s), a prefill burst cannot choke the monolith on
        # THROUGHPUT — the structural failure is latency: the monolith
        # runs every prefill inline in its one decode loop, so a burst
        # of max-length prompts STALLS the tokens of already-running
        # requests (inter-token latency spikes by the whole serialized
        # burst).  Fleet decode loops never share a thread with a
        # prefill — their running requests only lose the CPU slice the
        # prefill workers take.  We measure both systems' running-
        # request ITL and steady-arrival TTFT through one identical
        # burst.
        long_len = MAX_SEQ - 4            # max-length prompts, 2 new
        t_burst = args.burst_seconds * 0.4
        burst_window = 2.5

        def burst_run(rtr, tag):
            # warm the long-prompt prefill bucket out-of-band so the
            # choke we measure is scheduling, not first-compile
            rng = random.Random(43)
            warm = [rng.randrange(VOCAB) for _ in range(long_len)]
            rtr.generate(warm, 2, req_id="%s-warm" % tag) \
               .result(timeout=180)
            sched = _schedule(41, int(args.burst_rate
                                      * args.burst_seconds),
                              args.burst_rate, lo=4, hi=14, prefix=tag)
            stop = threading.Event()

            def drop_burst():
                time.sleep(t_burst)
                if stop.is_set():
                    return
                for i in range(args.burst_width):
                    p = [rng.randrange(VOCAB) for _ in range(long_len)]
                    rtr.generate(p, 2, req_id="%s-long%d" % (tag, i))
            th = threading.Thread(target=drop_burst, daemon=True)
            th.start()
            recs, summ = _replay(rtr, sched, args.max_new)
            stop.set()
            th.join(timeout=30)
            ok = [r for r in recs if r["ok"]]
            # running during the burst: arrived just before or while
            # the burst drains (max_new=32 decodes span the window)
            during = [r for r in ok
                      if t_burst - 0.4 <= r["t_arr"]
                      <= t_burst + burst_window]
            pre = [r for r in ok if r["t_arr"] < t_burst - 0.5]
            pre_itl = max(0.1, _pctl([r["itl_max_ms"] for r in pre],
                                     50))
            return {"lost": summ["lost"],
                    "steady_itl_p50_ms": round(pre_itl, 2),
                    "burst_itl_p99_ms": round(
                        _pctl([r["itl_max_ms"] for r in during], 99),
                        2),
                    "itl_choke_x": round(
                        _pctl([r["itl_max_ms"] for r in during], 99)
                        / pre_itl, 2),
                    "steady_ttft_p99_ms": round(
                        _pctl([r["ttft_ms"] for r in pre], 99), 2),
                    "burst_ttft_p99_ms": round(
                        _pctl([r["ttft_ms"] for r in during], 99), 2)}

        mono = burst_run(solo_router, "m")
        fleet_b = burst_run(router, "f")
        solo_router.close()
        isolation = mono["itl_choke_x"] / max(1e-9,
                                              fleet_b["itl_choke_x"])
        out["burst"] = {"monolith": mono, "fleet": fleet_b,
                        "monolith_choke_x": mono["itl_choke_x"],
                        "fleet_isolation_x": round(isolation, 2)}

        # -- 4. kill drill: same schedule, healthy then SIGKILLed -----
        router.close()
        decode_names = [w.name for w in procs
                        if w.role == "decode" and w.name != "s0"]
        _arm_slos(decode_names, tsdb_dir, dump_dir)
        router = FleetRouter(tr, members, lease_s=1.5,
                             lease_interval_s=0.4, hedge_s=1.5,
                             deadline_s=120.0, max_attempts=5)
        kill_rate = max(4.0, 0.30 * args.rate_x * floor["tokens_s"]
                        / args.max_new)
        sched = _schedule(51, int(kill_rate * args.kill_seconds),
                          kill_rate, prefix="k")
        base_recs, base = _replay(router, sched, args.max_new)
        base_map = {r["rid"]: r["tokens"] for r in base_recs
                    if r["ok"]}
        victims = [] if args.kill == "none" else \
            ["d1"] + (["p1"] if args.kill == "both"
                      and prefills > 1 else [])
        ev0 = _counter("fleet_evictions_total")

        def sigkill():
            for v in victims:
                by_name[v].proc.kill()
        t_kill = args.kill_seconds * 0.35
        kill_recs, kill = (_replay(router, sched, args.max_new,
                                   kill_at=t_kill, kill_fn=sigkill)
                           if args.kill != "none"
                           else _replay(router, sched, args.max_new))
        parity = all(r["ok"] and base_map.get(r["rid"]) == r["tokens"]
                     for r in kill_recs)
        pre = [r["ttft_ms"] for r in kill_recs
               if r["ok"] and r["t_arr"] < t_kill]
        recovery_s, thresh = _ttft_recovery(
            kill_recs, kill.get("killed_at_s", t_kill),
            _pctl(pre, 99))
        slo_out = _slo_verdict(await_s=10.0 if args.kill != "none"
                               else 0.0)
        artifacts = _eviction_artifacts(dump_dir, set(victims))
        out["kill"] = dict(
            kill, mode=args.kill, victims=victims, parity=parity,
            evictions=_counter("fleet_evictions_total") - ev0,
            pre_kill_ttft_p99_ms=round(_pctl(pre, 99), 2),
            ttft_recovery_s=recovery_s,
            ttft_recovery_threshold_ms=round(thresh, 1),
            artifacts=artifacts)
        out["baseline"] = {"lost": base["lost"],
                           "tokens_s": base["tokens_s"],
                           "ttft_p99_ms": base["ttft_p99_ms"]}
        out["slo"] = slo_out

        # -- 5. torn migration (in-process, same codec) ---------------
        out["torn"] = _torn_drill(dump_dir)

        # -- graceful drain: survivors must exit 0 --------------------
        drained = {}
        for w in procs:
            if w.name in victims:
                continue
            drained[w.name] = bool(
                _drain_direct(tr, w.addr).get("drained"))
        router.close()
        out["drained"] = drained
    finally:
        exits = _reap(procs)
        tr.close()
    out["worker_exits"] = exits
    survivors = [w.name for w in procs
                 if w.name not in out.get("kill", {}).get("victims", [])]
    kill_ok = (args.kill == "none"
               or (out["kill"]["lost"] == 0 and out["kill"]["parity"]
                   and out["kill"]["evictions"] >= len(victims)
                   and all(v in out["kill"]["artifacts"]
                           for v in victims)
                   and out["kill"]["ttft_recovery_s"] is not None
                   and out["kill"]["ttft_recovery_s"] <= 5.0
                   and out["slo"]["availability_alert"]))
    out["gates"] = {
        "scaling": out["scale"]["scaling_x"]
        >= out["scale"]["scaling_target"],
        "no_lost_scale": out["scale"]["lost"] == 0,
        "burst_monolith_chokes": out["burst"]["monolith_choke_x"] >= 2.0,
        "burst_fleet_holds": out["burst"]["fleet_isolation_x"] >= 2.0,
        "kill_survived": bool(kill_ok),
        "torn_named": out["torn"]["ok"],
        "drain_exit_zero": all(exits.get(n) == 0 for n in survivors),
    }
    out["ok"] = all(out["gates"].values())
    return out


def _sentinel_check(out):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from perf_sentinel import sentinel_gate
    return sentinel_gate(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="in-process tier-1 smoke (LocalTransport)")
    ap.add_argument("--seconds", type=float, default=20.0,
                    help="scaling-phase Poisson duration")
    ap.add_argument("--floor-seconds", type=float, default=6.0)
    ap.add_argument("--kill-seconds", type=float, default=14.0)
    ap.add_argument("--burst-seconds", type=float, default=10.0)
    ap.add_argument("--burst-rate", type=float, default=16.0)
    ap.add_argument("--burst-width", type=int, default=24,
                    help="long prompts dropped at the burst instant")
    ap.add_argument("--rate-x", type=float, default=1.25,
                    help="offered token rate as a multiple of the floor")
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--replicas", type=int, default=4,
                    help="decode worker processes")
    ap.add_argument("--prefill-workers", type=int, default=2)
    ap.add_argument("--kill", default="decode",
                    choices=("decode", "both", "none"))
    ap.add_argument("--out", default=None)
    ap.add_argument("--sentinel", action="store_true",
                    help="self-gate against PERF_TRAJECTORY.json")
    args = ap.parse_args(argv)

    dump_dir = os.environ.get("FLAGS_telemetry_dump_dir") \
        or tempfile.mkdtemp(prefix="fleet_dump_")
    tsdb_dir = tempfile.mkdtemp(prefix="fleet_tsdb_")
    FLAGS.telemetry_dump_dir = dump_dir
    t0 = time.time()
    out = run_quick(args, dump_dir, tsdb_dir) if args.quick \
        else run_full(args, dump_dir, tsdb_dir)
    out["metric"] = "serve_fleet_bench"
    out["quick"] = bool(args.quick)
    out["elapsed_s"] = round(time.time() - t0, 1)
    out["dump_dir"] = dump_dir
    out["conn_failures"] = _counter("serve_conn_failures_total")
    line = json.dumps(out, sort_keys=True)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    rc = 0 if out["ok"] else 1
    return rc or (_sentinel_check(out) if args.sentinel else 0)


if __name__ == "__main__":
    sys.exit(main())
