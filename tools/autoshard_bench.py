#!/usr/bin/env python
"""Auto-sharding bench: the ISSUE 20 proof artifact.

Three claims, measured on the 8-device virtual CPU mesh (same rig as
tools/mesh_profile.py — host numbers are indicative, ratios and
rankings are the portable part):

1. **Auto vs hand**: `spmd.auto_shard` places the same tiny
   transformer at p ∈ {2, 4, 8} and its measured step time lands
   within 10% of the best hand-picked MESH_PROFILE strategy at that p
   (the Alpa-style claim: search over measured costs matches
   hand-tuning).  The artifact records, per strategy, the cost model's
   *predicted* ms next to the *measured* ms and the provenance of
   every cost term (autotune / tsdb / mesh_profile fit / roofline) —
   no cost term without a source.

2. **Elastic shrink**: a timed mid-run 8→4 mesh shrink — quiesce the
   prepared state, re-lower the SAME annotated program, rebuild — with
   loss-trajectory parity at quiesce: the post-shrink losses match a
   reference run that never resharded (placement changes, math does
   not).

3. **Self-gating**: --sentinel checks the run against the recorded
   PERF_TRAJECTORY floors (ratio metrics, not raw CPU wall — a gap
   fraction is stable where milliseconds are not).

Usage:
    python tools/autoshard_bench.py [--steps N] [--quick]
                                    [--out AUTOSHARD_BENCH.json]
                                    [--sentinel]
    python tools/autoshard_bench.py --shrink-drill --dump-dir D
    python tools/autoshard_bench.py --shrink-drill --dump-dir D --recover

The --shrink-drill modes are the fault_matrix 'reshard' preset's
worker: the run phase trains, checkpoints (PR 1), writes the expected
post-quiesce loss trajectory, touches ``pre_shrink_ready`` and pauses
inside the shrink window so the parent can SIGKILL it mid-shrink; the
--recover phase restarts from the shard checkpoint, re-lowers for the
shrunken mesh, and must reproduce the expected trajectory and leave a
flight artifact.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_DEV = 8
GLOBAL_BATCH = 8
SEQ = 64
MODEL = dict(vocab_size=64, seq_len=SEQ, d_model=128, n_head=4,
             n_layers=2, d_ff=256)

# hand-picked strategies per device count — the MESH_PROFILE carriers
# expressible on the annotation path (pp runs a different program shape;
# mesh_profile keeps measuring it on the pipeline lowering)
HAND = {
    2: [("dp2", {"dp": 2}), ("tp2", {"tp": 2})],
    4: [("dp4", {"dp": 4}), ("dp2xtp2", {"dp": 2, "tp": 2}),
        ("dp2xsp2", {"dp": 2, "sp": 2})],
    8: [("dp8", {"dp": 8}), ("dp4xtp2", {"dp": 4, "tp": 2}),
        ("dp2xtp2xsp2", {"dp": 2, "tp": 2, "sp": 2}),
        ("dp4xep2", {"dp": 4, "ep": 2})],
}
# sp/ep legs need the ring/moe program wiring; --quick keeps the
# dp/tp-only spine (and says so in the artifact — no silent truncation)
QUICK_SKIP = {"dp2xsp2", "dp2xtp2xsp2", "dp4xep2"}

PARITY_TOL = 5e-3  # max relative loss divergence at quiesce


def _force_cpu():
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=%d" % N_DEV)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import __graft_entry__ as graft
    graft._force_cpu_platform(N_DEV)
    # the measured-cost loop needs a TSDB to write hand-leg step times
    # into (and for auto_shard to read back); a throwaway store when
    # the operator didn't point FLAGS_tsdb_dir somewhere durable
    from paddle_tpu.core.flags import FLAGS
    if not FLAGS.tsdb_dir:
        FLAGS.tsdb_dir = tempfile.mkdtemp(prefix="autoshard_tsdb_")


def _build(axes=None, annotate_for=None, placement=None):
    """One transformer program + scope; ``axes`` wires the hand
    strategy flags (tp/sp/ep), ``annotate_for``/``placement`` routes
    through spmd instead.  Returns (program, scope, loss, feed names,
    executor-ready mesh_axes or None, placement)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models.transformer import get_model
    from paddle_tpu.parallel import spmd

    axes = dict(axes or {})
    kwargs = dict(MODEL)
    if axes.get("ep", 1) > 1:
        kwargs.update(moe_experts=4, ep=True)
    else:
        kwargs.update(tp=axes.get("tp", 1) > 1,
                      sp=axes.get("sp", 1) > 1)
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss, (src, label), _ = get_model(
                    batch_size=GLOBAL_BATCH, **kwargs)
        fluid.Executor(fluid.CPUPlace()).run(startup)
    pl = placement
    if annotate_for is not None and pl is None:
        pl = spmd.auto_shard(main, annotate_for,
                             batch_size=GLOBAL_BATCH)
    if pl is not None:
        spmd.apply_placement(main, pl, scope=scope)
        axes = None  # executor infers the mesh from the stash
    return main, scope, loss, (src.name, label.name), axes, pl


def _feed(names, rng):
    import numpy as np
    src, label = names
    xs = rng.randint(0, MODEL["vocab_size"],
                     (GLOBAL_BATCH, SEQ)).astype(np.int64)
    ys = np.roll(xs, -1, axis=1)[:, :, None].astype(np.int64)
    return {src: xs, label: ys}


def _measure(main, scope, loss, names, axes, p, steps):
    """(step_ms, last_loss): warmup + timed steps through the
    ParallelExecutor — the annotated route when axes is None."""
    import numpy as np
    import paddle_tpu.fluid as fluid

    pe = fluid.ParallelExecutor(
        use_tpu=False, loss_name=loss.name, main_program=main,
        scope=scope, mesh_axes=axes, num_devices=p)
    rng = np.random.RandomState(0)
    feed = _feed(names, rng)
    pe.run(feed=feed, fetch_list=[loss])  # warmup/compile
    t0 = time.perf_counter()
    out = None
    for _ in range(steps):
        out, = pe.run(feed=feed, fetch_list=[loss])
    last = float(np.asarray(out).reshape(-1)[0])
    return (time.perf_counter() - t0) / steps * 1e3, last


def _predict(main, axes, cost):
    """Cost-model prediction for one strategy on this program; returns
    (predicted_ms, trace)."""
    from paddle_tpu.parallel import spmd
    cost.trace = []
    predicted, _model_ms, _hist, _specs, _dec = spmd._strategy_cost(
        main.desc, axes, cost, GLOBAL_BATCH)
    return predicted, list(cost.trace)


def _source_census(traces):
    census = {}
    for tr in traces:
        for term in tr:
            src = term.get("source", "?").split(":")[0]
            census[src] = census.get(src, 0) + 1
    return census


def _record_history(rows):
    """Best-effort: feed measured step times back into the TSDB so the
    next search predicts strategies the rig has already run from their
    own history (CostModel source ``tsdb:autoshard.step_ms.*``)."""
    try:
        from paddle_tpu.observability import tsdb as _tsdb
        store = _tsdb.default_store(create=True)
        if store is None:
            return False
        for r in rows:
            if r.get("step_ms"):
                store.append("autoshard.step_ms.%s" % r["strategy"],
                             float(r["step_ms"]))
        store.flush()
        return True
    except Exception:
        return False


def run_bench(steps, quick):
    from paddle_tpu.parallel import spmd

    out = {"metric": "autoshard_bench", "quick": bool(quick),
           "n_dev": N_DEV, "global_batch": GLOBAL_BATCH,
           "model": dict(MODEL), "steps": steps, "per_p": {},
           "skipped_strategies": []}
    traces = []
    for p in (2, 4, 8):
        legs = []
        for name, axes in HAND[p]:
            if quick and name in QUICK_SKIP:
                out["skipped_strategies"].append(name)
                continue
            main, scope, loss, names, maxes, _ = _build(axes=axes)
            cost = spmd.CostModel.from_repo()
            predicted, trace = _predict(main, dict(axes), cost)
            traces.append(trace)
            ms, _ = _measure(main, scope, loss, names, maxes, p, steps)
            legs.append({"strategy": name, "axes": axes,
                         "step_ms": round(ms, 2),
                         "predicted_ms": round(predicted, 2),
                         "pred_err_pct": round(
                             (predicted - ms) / ms * 100.0, 1),
                         "cost_terms": len(trace)})
            print("p=%d %-12s %8.2f ms (predicted %7.2f)"
                  % (p, name, ms, predicted), flush=True)
        # hand measurements feed the TSDB FIRST: the auto search then
        # predicts every already-measured strategy from its own history
        # and pessimistically calibrates the rest (spmd.auto_shard)
        out["history_recorded"] = (_record_history(legs)
                                   or out.get("history_recorded", False))
        # the auto leg: plain program, placement chosen by prediction
        # alone, measured through the annotated-executor route
        main, scope, loss, names, maxes, pl = _build(annotate_for=p)
        traces.append(pl.trace)
        reused = next((l for l in legs if l["strategy"] == pl.strategy),
                      None)
        if reused is not None:
            auto_ms = reused["step_ms"]
        else:
            auto_ms, _ = _measure(main, scope, loss, names, maxes, p,
                                  steps)
        best = min(legs, key=lambda l: l["step_ms"])
        gap = auto_ms / best["step_ms"]
        out["per_p"][str(p)] = {
            "strategies": legs,
            "auto": {"strategy": pl.strategy,
                     "mesh_axes": dict(pl.mesh_axes),
                     "step_ms": round(auto_ms, 2),
                     "predicted_ms": round(pl.predicted_ms, 2),
                     "n_annotated": len(pl.var_shardings),
                     "reused_leg": bool(reused),
                     "trace": pl.trace},
            "best_hand": best["strategy"],
            "best_hand_ms": best["step_ms"],
            "auto_gap_frac": round(max(1.0, gap), 4),
            "auto_within_10pct": bool(gap <= 1.10),
        }
        print("p=%d auto=%-12s %8.2f ms  best_hand=%s %.2f ms  "
              "gap=%.3f" % (p, pl.strategy, auto_ms, best["strategy"],
                            best["step_ms"], gap), flush=True)
        if reused is None:
            _record_history([{"strategy": pl.strategy,
                              "step_ms": auto_ms}])
    out["cost_sources"] = _source_census(traces)
    out["reshard"] = run_shrink(steps=max(2, min(steps, 3)))
    return out


def run_shrink(steps=3, checkpoint_dir=None, pause_s=0.0,
               marker=None):
    """The timed 8→4 shrink with loss-trajectory parity at quiesce.

    Train at p=8 on the auto placement, quiesce, snapshot, run the
    reference continuation on the UNCHANGED mesh, restore the
    snapshot, reshard to 4, and replay the same feeds — the two loss
    trajectories must agree to PARITY_TOL.  ``checkpoint_dir`` saves a
    PR 1 shard checkpoint at the quiesce point (the fault drill's
    recovery source); ``marker``/``pause_s`` open the kill window for
    the 'reshard' preset."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel import spmd

    rec = {"from": N_DEV, "to": N_DEV // 2, "steps": steps}
    main, scope, loss, names, _, pl = _build(annotate_for=N_DEV)
    rec["strategy_before"] = pl.strategy
    pe = fluid.ParallelExecutor(use_tpu=False, loss_name=loss.name,
                                main_program=main, scope=scope,
                                num_devices=N_DEV)
    rng = np.random.RandomState(0)
    for _ in range(steps):
        pe.run(feed=_feed(names, rng), fetch_list=[loss])

    # quiesce: prepared device state flushes back through the scope
    t0 = time.perf_counter()
    scope.flush_prepared()
    block = main.global_block()
    persist = [n for n, v in block.vars.items()
               if v.persistable and scope.has_var(n)]
    snapshot = {n: np.array(np.asarray(scope.find_var(n)), copy=True)
                for n in persist}
    rec["quiesce_ms"] = round((time.perf_counter() - t0) * 1e3, 2)

    if checkpoint_dir:
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            from paddle_tpu.fluid import io as fio
            rec["checkpoint_serial"] = fio.save_checkpoint(
                exe, checkpoint_dir, main_program=main)

    # reference continuation: same feeds, mesh unchanged
    feed_rng = np.random.RandomState(1234)
    feeds = [_feed(names, feed_rng) for _ in range(steps)]
    ref = []
    for f in feeds:
        o, = pe.run(feed=f, fetch_list=[loss])
        ref.append(float(np.asarray(o).reshape(-1)[0]))
    rec["ref_losses"] = [round(v, 6) for v in ref]
    # rewind to the quiesce point (external write wins over prepared)
    for n, v in snapshot.items():
        scope.set(n, v)

    if marker:
        # the recovery phase replays this trajectory, so it must be
        # durable BEFORE the kill window opens
        with open(os.path.join(os.path.dirname(marker),
                               "expected.json"), "w") as f:
            json.dump({"ref_losses": rec["ref_losses"],
                       "steps": steps}, f)
        with open(marker, "w") as f:
            f.write("pre_shrink\n")
    if pause_s:
        time.sleep(pause_s)  # the preset's SIGKILL window

    t0 = time.perf_counter()
    pe2, report = spmd.reshard(main, scope, N_DEV // 2,
                               batch_size=GLOBAL_BATCH)
    rec["reshard_total_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
    rec.update({k: round(v, 2) if isinstance(v, float) else v
                for k, v in report.items()
                if k in ("quiesce_ms", "relower_ms", "rebuild_ms",
                         "strategy", "mesh_axes", "verify_errors",
                         "flight_artifact")})
    rec["strategy_after"] = report.get("strategy")

    got = []
    for f in feeds:
        o, = pe2.run(feed=f, fetch_list=[loss])
        got.append(float(np.asarray(o).reshape(-1)[0]))
    rec["post_losses"] = [round(v, 6) for v in got]
    rel = [abs(a - b) / max(abs(b), 1e-9) for a, b in zip(got, ref)]
    rec["parity_max_rel"] = round(max(rel), 8)
    rec["parity_ok"] = bool(max(rel) <= PARITY_TOL)
    rec["parity_tol"] = PARITY_TOL
    print("shrink %d->%d: %s -> %s, total %.0f ms, parity max rel "
          "%.2e (%s)" % (rec["from"], rec["to"], rec["strategy_before"],
                         rec["strategy_after"],
                         rec["reshard_total_ms"], max(rel),
                         "ok" if rec["parity_ok"] else "FAIL"),
          flush=True)
    return rec


# ---------------------------------------------------------------------------
# fault_matrix 'reshard' preset worker
# ---------------------------------------------------------------------------

def run_drill(dump_dir, steps=3):
    """Run phase: train → PR 1 checkpoint → expected trajectory →
    marker → pause (SIGKILL lands here) → finish the shrink anyway
    (so an un-killed drill still completes)."""
    ckpt = os.path.join(dump_dir, "ckpt")
    marker = os.path.join(dump_dir, "pre_shrink_ready")
    pause = float(os.environ.get("AUTOSHARD_DRILL_PAUSE_S", "5"))
    rec = run_shrink(steps=steps, checkpoint_dir=ckpt,
                     pause_s=pause, marker=marker)
    with open(os.path.join(dump_dir, "expected.json"), "w") as f:
        json.dump({"ref_losses": rec["ref_losses"],
                   "steps": steps}, f)
    with open(os.path.join(dump_dir, "drill_result.json"), "w") as f:
        json.dump(rec, f)
    return 0 if rec["parity_ok"] else 3


def run_drill_recover(dump_dir, steps=3):
    """Recover phase: the run phase wrote the checkpoint + expected
    trajectory and was SIGKILLed mid-shrink.  Rebuild the program,
    restore the PR 1 shard checkpoint, reshard to the shrunken mesh,
    and reproduce the expected post-quiesce losses."""
    import numpy as np
    from paddle_tpu.parallel import spmd

    with open(os.path.join(dump_dir, "expected.json")) as f:
        expected = json.load(f)
    steps = int(expected.get("steps", steps))
    ckpt = os.path.join(dump_dir, "ckpt")
    main, scope, loss, names, _, _ = _build(annotate_for=N_DEV)
    pe2, report = spmd.reshard(main, scope, N_DEV // 2,
                               batch_size=GLOBAL_BATCH,
                               checkpoint_dir=ckpt,
                               flight_reason="reshard_recovery")
    feed_rng = np.random.RandomState(1234)
    got = []
    for _ in range(steps):
        o, = pe2.run(feed=_feed(names, feed_rng), fetch_list=[loss])
        got.append(float(np.asarray(o).reshape(-1)[0]))
    ref = expected["ref_losses"]
    rel = [abs(a - b) / max(abs(b), 1e-9) for a, b in zip(got, ref)]
    rec = {"recovered": True, "post_losses": got,
           "ref_losses": ref,
           "parity_max_rel": round(max(rel), 8),
           "parity_ok": bool(max(rel) <= PARITY_TOL),
           "checkpoint_serial": report.get("checkpoint_serial"),
           "flight_artifact": report.get("flight_artifact"),
           "strategy_after": report.get("strategy")}
    with open(os.path.join(dump_dir, "drill_result.json"), "w") as f:
        json.dump(rec, f)
    print(json.dumps(rec))
    return 0 if rec["parity_ok"] else 3


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--quick", action="store_true",
                    help="2 timed steps, dp/tp strategies only")
    ap.add_argument("--out", default=None,
                    help="artifact path (default "
                         "<repo>/AUTOSHARD_BENCH.json)")
    ap.add_argument("--sentinel", action="store_true",
                    help="gate against PERF_TRAJECTORY floors; rc 3 "
                         "on >15%% regression")
    ap.add_argument("--shrink-drill", action="store_true",
                    help="fault_matrix worker mode")
    ap.add_argument("--recover", action="store_true",
                    help="with --shrink-drill: recovery phase")
    ap.add_argument("--dump-dir", default=None)
    args = ap.parse_args(argv)

    _force_cpu()
    if args.shrink_drill:
        if not args.dump_dir:
            ap.error("--shrink-drill needs --dump-dir")
        steps = 2 if args.quick else 3
        if args.recover:
            return run_drill_recover(args.dump_dir, steps=steps)
        return run_drill(args.dump_dir, steps=steps)

    steps = 2 if args.quick else args.steps
    out = run_bench(steps, args.quick)
    path = args.out or os.path.join(REPO, "AUTOSHARD_BENCH.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    print("wrote %s" % path)
    if args.sentinel:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import perf_sentinel
        return perf_sentinel.sentinel_gate(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
